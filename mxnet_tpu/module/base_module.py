"""BaseModule — the abstract training-loop interface.

Reference: ``python/mxnet/module/base_module.py`` (951 LoC; ``fit`` :369,
``score`` :197, ``forward_backward`` :191).  The fit loop is kept
line-compatible in behavior: bind → init_params → init_optimizer → per batch
forward_backward/update/update_metric with callbacks — the call stack in
SURVEY §3.1.
"""

from __future__ import annotations

import contextlib
import logging
import os
import signal as _signal
import threading
import time

import numpy as np

from .. import compile_cache as _compile_cache
from .. import faults as _faults
from .. import metric as _metric
from .. import perfdebug as _perfdebug
from .. import random as _random
from .. import sentinel as _sentinel
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from ..base import MXNetError
from ..elastic import MembershipChanged, StaleEpoch, \
    enabled as _elastic_enabled
from ..model import BatchEndParam
from ..initializer import Uniform

__all__ = ["BaseModule"]

#: control-flow exceptions that hand fit(elastic=True) back to the
#: reshard cycle: a typed stale-epoch rejection from the coordinator, or
#: the batch-boundary membership poll noticing an epoch bump
_ELASTIC_RESYNC = (StaleEpoch, MembershipChanged)

_NAN_POLICIES = ("raise", "skip_batch", "rollback")
#: ``anomaly_policy`` shares the nan_policy vocabulary: a statistical
#: spike is handled exactly like a NaN is (docs/resilience.md
#: "Statistical anomaly rollback")
_ANOMALY_POLICIES = _NAN_POLICIES
_AUDIT_POLICIES = ("raise", "rollback")

#: end-of-iterator sentinel for the phase-timed batch loop (a data batch
#: may legitimately be falsy, so ``None`` would be ambiguous)
_FIT_END = object()

#: resilience counters declared at zero when fit starts under telemetry,
#: so the family is visible in ``snapshot()`` even for a clean run
_RESILIENCE_COUNTERS = (
    "resilience.nan_batches", "resilience.recordio_skipped",
    "resilience.fault_injected", "resilience.checkpoint.saves",
    "resilience.checkpoint.resumes", "resilience.rollbacks",
    "resilience.checkpoint.corrupt_skipped",
    "resilience.checkpoint.async_dropped", "resilience.preemptions")


def _as_metric(m):
    return m if isinstance(m, _metric.EvalMetric) else _metric.create(m)


# -- graceful preemption (docs/resilience.md "Preemption & exact resume") --

#: process-wide owner of the SIGTERM/SIGINT handlers: exactly ONE fit
#: call may hold them — a nested fit (e.g. from a callback) refusing to
#: double-install is the hygiene contract the graftlint signal-restore
#: pass lints the restore half of
_fit_signal_lock = threading.Lock()
_fit_signal_owner = [None]


class _PreemptGuard:
    """Signal-to-flag bridge for one ``fit`` call: the handler only
    records the signal; the batch loop notices at the next boundary,
    finishes the in-flight batch, drains accumulators, checkpoints and
    raises :class:`~mxnet_tpu.checkpoint.TrainingPreempted`.  A SECOND
    signal while draining raises ``KeyboardInterrupt`` immediately — the
    operator insists."""

    __slots__ = ("requested",)

    def __init__(self):
        self.requested = None

    def __call__(self, signum, frame):
        if self.requested is not None:
            raise KeyboardInterrupt(
                "second signal %s during preemption drain" % signum)
        self.requested = signum


@contextlib.contextmanager
def _preempt_signals(guard, logger, enable=True):
    """Install ``guard`` as the SIGTERM/SIGINT handler for the scope,
    restoring the previous handlers on ANY exit path (the try/finally
    is what the graftlint signal-restore pass enforces).  ``enable=False``
    (fit without ``checkpoint_prefix``) leaves the process handlers
    untouched — a plain fit keeps its KeyboardInterrupt semantics.
    Outside the main thread Python forbids handler installation; fit
    then runs without graceful preemption (logged once)."""
    if not enable:
        yield guard
        return
    if threading.current_thread() is not threading.main_thread():
        logger.debug("fit: not on the main thread; SIGTERM/SIGINT "
                     "graceful drain is unavailable here")
        yield guard
        return
    with _fit_signal_lock:
        if _fit_signal_owner[0] is not None:
            raise MXNetError(
                "a fit call already owns the process SIGTERM/SIGINT "
                "handlers (nested fit from a callback?): refusing to "
                "double-install — run the inner fit after the outer one "
                "finishes, or in a separate process")
        _fit_signal_owner[0] = guard
    prev_term = _signal.signal(_signal.SIGTERM, guard)
    try:
        prev_int = _signal.signal(_signal.SIGINT, guard)
        try:
            yield guard
        finally:
            _signal.signal(_signal.SIGINT, prev_int)
    finally:
        _signal.signal(_signal.SIGTERM, prev_term)
        with _fit_signal_lock:
            _fit_signal_owner[0] = None


@contextlib.contextmanager
def _sigquit_dump(logger):
    """Dump-on-demand for the fit scope: SIGQUIT (Ctrl-\\) writes a
    flight-recorder + all-thread-stack dump WITHOUT killing the run —
    the operator's "what is it doing right now" probe for a live job.
    Same installer/finally-restore discipline as :func:`_preempt_signals`
    (the graftlint signal-restore pass lints the restore half); the
    handler only dumps, never raises, so training continues.  Main
    thread only (Python forbids installs elsewhere); a nested fit just
    replaces the outer fit's identical handler and restores it on
    exit."""
    sig = getattr(_signal, "SIGQUIT", None)
    if sig is None or \
            threading.current_thread() is not threading.main_thread():
        yield
        return

    def handler(signum, frame):
        # the dump runs on a SPAWNED thread, not inline: handlers
        # execute between bytecodes of the interrupted frame, which may
        # hold the (non-reentrant) telemetry/flight-recorder locks the
        # dump needs — an inline dump would deadlock the training
        # thread against itself.  Spawn-and-return lets the interrupted
        # frame release its locks, and still works when the training
        # thread is wedged in a C call (the usual reason to probe)
        logger.warning("SIGQUIT: dumping flight recorder + thread "
                       "stacks (run continues)")
        threading.Thread(target=_sentinel.dump_on_demand,
                         args=("sigquit",), name="sigquit-dump",
                         daemon=True).start()

    prev = _signal.signal(sig, handler)
    try:
        yield
    finally:
        _signal.signal(sig, prev)


def _adapt_iter_state(state, target):
    """Bridge an iterator-state capture across a prefetch-wrapping
    difference between the killed and the resumed run: a wrapper state
    unwraps onto a plain iterator (single sub-iterator only) and a plain
    state wraps for a wrapper target."""
    from ..io import PrefetchingIter

    wrapper_state = isinstance(state, dict) and \
        state.get("type") in ("PrefetchingIter", "DevicePrefetchIter")
    if isinstance(target, PrefetchingIter):
        if not wrapper_state:
            return {"type": type(target).__name__, "inner": [state]}
        return state
    if wrapper_state and len(state.get("inner", [])) == 1:
        return state["inner"][0]
    return state


class _FitRun:
    """Per-``fit`` resilience plumbing: the batch-granular snapshot
    cadence, the async writer, the preemption drain sequence, and —
    for ``fit(elastic=True)`` — the elastic ledger-commit/membership-poll
    hooks."""

    def __init__(self, prefix, every_n, writer, guard, logger,
                 keep_last=None, elastic=None):
        self.prefix = prefix
        self.every_n = every_n
        self.writer = writer
        self.guard = guard
        self.logger = logger
        self.keep_last = keep_last
        self.elastic = elastic
        self._warned_iter = False

    def capture(self, module, epoch, nbatch, fit_data, eval_metric):
        """One :class:`~mxnet_tpu.checkpoint.Snapshot`: device copies of
        the big arrays (no host sync), host dicts for the smalls.  The
        metric capture syncs the device-metric accumulator — that IS the
        drain step — and the iterator capture drains the prefetch
        queue."""
        from .. import checkpoint as _ckpt

        if hasattr(module, "_capture_state_arrays"):
            arg, aux, opt_states, opt_counts = \
                module._capture_state_arrays()
        else:
            arg_l, aux_l = module.get_params()
            arg = {k: v.copy() for k, v in arg_l.items()}
            aux = {k: v.copy() for k, v in aux_l.items()}
            opt_states = opt_counts = None
        rng = {"global": _random.get_state()}
        ex = getattr(module, "_exec", None)
        if ex is not None:
            rng["exec_step"] = int(getattr(ex, "_rng_step", 0))
        try:
            iter_state = fit_data.state_dict()
        except NotImplementedError:
            if not self._warned_iter:
                self.logger.warning(
                    "checkpoint snapshot: %s has no iterator-state "
                    "protocol; mid-epoch resume will degrade to the "
                    "epoch boundary", type(fit_data).__name__)
                self._warned_iter = True
            iter_state = None
        try:
            metric_state = eval_metric.get_state()
        except NotImplementedError:
            metric_state = None
        mesh_info = None
        get_info = getattr(module, "_snapshot_mesh_info", None)
        if callable(get_info):
            # kvstore='mesh' with world > 1: the generation writes as
            # per-shard payload files + a stitching manifest entry
            mesh_info = get_info()
        snap = _ckpt.Snapshot(epoch, nbatch, arg, aux,
                              opt_states=opt_states,
                              opt_counts=opt_counts, rng_state=rng,
                              metric_state=metric_state,
                              iter_state=iter_state,
                              mesh_info=mesh_info)
        if self.elastic is not None:
            # fold the coordinator-side optimizer states in: elastic
            # rehydration restores the server's momentum from the snapshot
            self.elastic.augment_snapshot(snap)
        return snap

    def after_batch(self, module, epoch, nbatch, fit_data, eval_metric,
                    drain_guard=None, data_batch=None):
        """Bottom-of-batch hook: commit the batch to the elastic data
        ledger, take the cadence snapshot, honor a pending preemption
        (the in-flight batch is complete by now), then poll elastic
        membership — a change raises out to the reshard cycle."""
        if self.elastic is not None:
            self.elastic.commit(data_batch)
        if self.every_n is not None and (nbatch + 1) % self.every_n == 0 \
                and (self.elastic is None or self.elastic.is_leader()):
            # elastic fits share one prefix across ranks: only the
            # membership leader writes, so generations never interleave
            self.writer.submit(
                self.capture(module, epoch, nbatch, fit_data, eval_metric))
        self.check_preempt(module, epoch, nbatch, fit_data, eval_metric,
                           drain_guard)
        if self.elastic is not None:
            self.elastic.poll(epoch, nbatch)

    def epoch_end_preempt(self, module, epoch, already_saved):
        """Preemption noticed at the epoch boundary: epoch ``epoch`` is
        fully complete (metrics logged, eval done, iterator reset), so
        the resume point is the epoch-``epoch + 1`` checkpoint — written
        here if the cadence had not already produced it."""
        from .. import checkpoint as _ckpt

        signum = self.guard.requested
        path = None
        if self.prefix is not None and \
                (self.elastic is None or self.elastic.is_leader()):
            # elastic ranks share one prefix: only the membership leader
            # writes the drain checkpoint (same single-writer rule as the
            # cadence snapshots); a preempted non-leader just leaves — the
            # survivors reshard from the leader's generations
            if not already_saved:
                arg_params_, aux_params_ = module.get_params()
                module._save_fit_checkpoint(self.prefix, epoch + 1,
                                            arg_params_, aux_params_)
            path = "%s-%04d.params" % (self.prefix, epoch + 1)
        _telemetry.inc("resilience.preemptions")
        _telemetry.event("preemption", epoch=epoch, nbatch=None,
                         signal=signum, checkpoint=path)
        _perfdebug.flight_dump("preemption", epoch=epoch, nbatch=None,
                               signal=signum, checkpoint=path)
        self.logger.warning(
            "preempted (signal %s) during epoch %d wrap-up: epoch "
            "complete, checkpoint %s", signum, epoch,
            path if path else "skipped (no checkpoint_prefix)")
        raise _ckpt.TrainingPreempted(
            "training preempted by signal %s at the end of epoch %d "
            "(epoch complete; resume with resume='auto')"
            % (signum, epoch), checkpoint_path=path, epoch=epoch,
            nbatch=None, signum=signum)

    def check_preempt(self, module, epoch, nbatch, fit_data, eval_metric,
                      drain_guard=None):
        from .. import checkpoint as _ckpt

        if self.guard is None or self.guard.requested is None:
            return
        signum = self.guard.requested
        # drain order: NaN-guard flag first (a poisoned final batch must
        # not be checkpointed unflagged), then the capture itself syncs
        # the device-metric accumulator and the prefetch queue
        if drain_guard is not None:
            drain_guard()
        path = None
        if self.prefix is not None and \
                (self.elastic is None or self.elastic.is_leader()):
            # single-writer rule under a shared elastic prefix (see
            # epoch_end_preempt): a preempted non-leader writes nothing —
            # concurrent same-generation writes from racing ranks could
            # interleave params/states files across writers
            snap = self.capture(module, epoch, nbatch, fit_data,
                                eval_metric)
            if self.writer is not None:
                # wait out an in-flight async write (≤1 by construction),
                # then write the final snapshot synchronously.  A STALE
                # background-write failure must not abort the drain —
                # the final snapshot below is exactly what a preempted
                # worker needs most
                try:
                    self.writer.drain()
                except Exception as e:  # noqa: broad-except — logged;
                    # the synchronous final write raises its own errors
                    self.logger.warning(
                        "preemption drain: earlier async snapshot write "
                        "had failed (%s); writing the final snapshot "
                        "anyway", e)
            path = _ckpt.write_snapshot(self.prefix, snap,
                                        logger=self.logger,
                                        keep_last=self.keep_last)
        _telemetry.inc("resilience.preemptions")
        _telemetry.event("preemption", epoch=epoch, nbatch=nbatch,
                         signal=signum, checkpoint=path)
        # the post-mortem record: last batches' phase timings, compiled-
        # executable attribution and the preemption event itself survive
        # the process (docs/observability.md "Flight recorder")
        _perfdebug.flight_dump("preemption", epoch=epoch, nbatch=nbatch,
                               signal=signum, checkpoint=path)
        self.logger.warning(
            "preempted (signal %s) at epoch %d batch %d: in-flight batch "
            "finished, accumulators drained, checkpoint %s",
            signum, epoch, nbatch,
            path if path else "skipped (no checkpoint_prefix)")
        raise _ckpt.TrainingPreempted(
            "training preempted by signal %s at epoch %d batch %d "
            "(graceful drain complete; resume with resume='auto')"
            % (signum, epoch, nbatch), checkpoint_path=path, epoch=epoch,
            nbatch=nbatch, signum=signum)


class BaseModule:
    """reference ``base_module.py:56``"""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- high-level API ---------------------------------------------------
    def forward_backward(self, data_batch):
        """reference :191"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """reference :197 — like ``fit``, auto-selects the device metric
        path for eligible metrics (the wrapped metric object the caller
        passed is folded back into at the final sync, so its ``get()``
        stays correct)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        # wrap BEFORE reset: a cached device wrapper may hold unsynced
        # stats from a previous pass, and reset() clears both layers
        eval_metric = _metric.as_device(_as_metric(eval_metric))
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            # score emits no telemetry phases: tick the hang watchdog's
            # liveness clock so a long validation pass inside an armed
            # fit never reads as a wedged step (free when unarmed)
            _sentinel.note_progress()
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                batch_end_param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                                eval_metric=eval_metric,
                                                locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(batch_end_param)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """reference base_module.py iter_predict"""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """reference base_module.py predict"""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    "Cannot merge batches: mismatched output count"
            from ..ndarray import concatenate

            output_list2 = [concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, checkpoint_prefix=None, checkpoint_period=1,
            resume=None, nan_policy=None, nan_check_period=None,
            prefetch_to_device=None, checkpoint_every_n_batches=None,
            elastic=None, anomaly_policy=None,
            audit_every_n_batches=None):
        """reference ``base_module.py:369`` — THE training loop.

        Sync-free hot loop (docs/how_to/perf.md): eligible metrics are
        auto-wrapped in :class:`~mxnet_tpu.metric.DeviceMetric` (per-batch
        stats accumulate on device; reads at callback cadence are the only
        syncs — ``MXNET_DEVICE_METRIC=0`` restores the host path), the
        NaN guard is folded into the step as one in-graph reduction read
        every ``nan_check_period`` batches (``MXNET_NAN_CHECK_PERIOD``,
        default 1), and ``prefetch_to_device=True``
        (``MXNET_DEVICE_PREFETCH=1``) stages each batch's H2D copy on a
        background thread via :class:`~mxnet_tpu.io.DevicePrefetchIter`.

        Resilience extensions (docs/resilience.md):

        ``checkpoint_prefix``
            When set, an atomic checkpoint (params [+ optimizer states] +
            manifest) is written every ``checkpoint_period`` epochs and at
            the final epoch.  Additionally installs SIGTERM/SIGINT
            graceful-preemption handlers for the duration of the call
            (restored on exit): on signal the in-flight batch finishes,
            accumulators drain, a final mid-epoch snapshot is written and
            :class:`~mxnet_tpu.checkpoint.TrainingPreempted` is raised
            carrying the checkpoint path.
        ``checkpoint_every_n_batches``
            Batch-granular snapshot cadence (default: the
            ``MXNET_CKPT_EVERY_N_BATCHES`` env var; unset disables).
            Every N batches the params / optimizer states are captured as
            device-side copies (no host sync on the hot loop) and a
            background writer thread serializes them — manifest-last,
            sha256-recorded, ``MXNET_CKPT_KEEP_LAST`` generations
            retained (``MXNET_CKPT_ASYNC=0`` forces inline writes).  At
            most one snapshot is ever in flight; cadence ticks landing on
            a busy writer are dropped and counted.
        ``resume="auto"``
            Restart from the newest checkpoint OR mid-epoch snapshot
            under ``checkpoint_prefix`` that passes sha256 + load
            verification; corrupt generations are skipped with a warning
            and counted.  A mid-epoch snapshot resumes EXACTLY: params,
            optimizer states and update counts, RNG streams, metric
            accumulators and the data-iterator position are restored, so
            the resumed trajectory is bit-identical to an uninterrupted
            run (tests/test_preemption.py pins this).
        ``nan_policy``
            Per-batch NaN/Inf guard on loss and gradients (default: the
            ``MXNET_NAN_POLICY`` env var; None disables).  ``"raise"``
            aborts with MXNetError, ``"skip_batch"`` drops the batch's
            update, ``"rollback"`` restores the last valid checkpoint and
            drops the batch.  Tripped batches are visible to callbacks via
            ``BatchEndParam.nan_detected``/``nan_action``.  The check is a
            device-side reduction folded into the step; with
            ``nan_check_period=N`` the one-scalar flag read happens every
            N batches (amortized semantics: see docs/resilience.md).
        ``anomaly_policy``
            (default: the ``MXNET_ANOMALY_POLICY`` env var; None
            disables)  Statistical anomaly guard generalizing
            ``nan_policy``: the global gradient norm of every batch is
            z-scored against a rolling window
            (``MXNET_ANOMALY_WINDOW`` batches,
            ``MXNET_ANOMALY_ZSCORE`` sigmas) and a finite spike trips
            the same raise / skip_batch / rollback vocabulary — a loss
            explosion is handled like a NaN is today, BEFORE the
            poisoned update lands.  skip/rollback trips are bounded by
            the consecutive ``MXNET_ROLLBACK_BUDGET`` (exhaustion
            raises :class:`~mxnet_tpu.sentinel.AnomalyBudgetExhausted`).
            Costs one scalar read per batch, and a staged fused step is
            materialized two-phase (gradients must be inspectable) —
            like ``monitor``, this is a diagnosis-over-fusion trade.
        ``audit_every_n_batches``
            (default: the ``MXNET_AUDIT_EVERY_N_BATCHES`` env var;
            unset disables)  Cross-replica integrity audits for
            ``kvstore='mesh'`` fits: every N batches ONE extra jitted
            program folds per-param bit-pattern checksums per mesh
            replica and compares them in-graph (replicated state must
            agree EXACTLY; ZeRO-owned rows are covered post-gather
            through the params they re-enter) — one tiny host read.
            A mismatch emits ``reliability.divergence`` and, per
            ``MXNET_AUDIT_POLICY``, raises
            :class:`~mxnet_tpu.sentinel.ReplicaDivergence` or rolls
            back to the last good checkpoint.
        ``MXNET_WATCHDOG=1`` (env)
            Arms the hang watchdog for the duration of the call: a
            sentinel thread tracks per-batch progress against a
            deadline auto-calibrated from the rolling median step time
            and, on expiry, dumps the flight recorder + all-thread
            stacks and raises
            :class:`~mxnet_tpu.sentinel.TrainingWedged` in this thread
            (``MXNET_WATCHDOG_ACTION``: raise/warn/exit) instead of
            hanging forever.  Also maintains the
            ``MXNET_HEARTBEAT_FILE`` heartbeat ``tools/supervise.py``
            watches.  SIGQUIT during any fit writes the same dump
            without killing the run.
        ``elastic``
            (default: the ``MXNET_ELASTIC`` env var) Elastic membership
            (docs/resilience.md "Elastic membership & resharding"): the
            world size may change mid-job.  Requires a ``dist_*``
            kvstore and ``checkpoint_prefix`` (the snapshot protocol is
            the reshard transport; the cadence is PINNED to every batch
            — a sparser ``checkpoint_every_n_batches`` is a typed error
            and ``MXNET_CKPT_EVERY_N_BATCHES`` is ignored with a
            warning, because the manifest is the reshard rollback
            target and a sparser cadence would discard committed work).
            On a membership-epoch bump this fit quiesces at the next
            batch boundary, rendezvouses with the surviving/new members,
            rehydrates from the newest snapshot generation and continues
            in-loop — the process never restarts, and two replays of the
            same elasticity schedule are bit-identical.  Pair
            ``train_data`` with an :class:`~mxnet_tpu.io.ElasticShardIter`
            so the data partition reshards with the world.  NOTE: the
            initial rendezvous also adopts the newest snapshot
            generation already under ``checkpoint_prefix`` — a mid-job
            joiner is indistinguishable from a fresh start, so
            ``elastic=True`` implies ``resume="auto"`` semantics; give
            a fresh job a fresh prefix.
        """
        assert num_epoch is not None, "please specify number of epochs"

        if elastic is None:
            elastic = _elastic_enabled()
        if elastic:
            if checkpoint_prefix is None:
                raise MXNetError(
                    "fit(elastic=True) needs checkpoint_prefix: the "
                    "snapshot manifest is the reshard transport")
            # elastic rollback granularity IS the snapshot cadence, and
            # it is pinned to every batch: a sparser cadence would
            # discard up to N-1 committed batches per membership change
            # and widen the no-generation reshard window the ledger
            # fallback is built around (io.py ElasticShardIter.reshard)
            if checkpoint_every_n_batches is not None \
                    and checkpoint_every_n_batches > 1:
                raise MXNetError(
                    "fit(elastic=True) snapshots every batch (the "
                    "manifest is the reshard rollback target); got "
                    "checkpoint_every_n_batches=%d"
                    % checkpoint_every_n_batches)
            env_n = int(os.environ.get(
                "MXNET_CKPT_EVERY_N_BATCHES", "0") or 0)
            if env_n > 1:
                self.logger.warning(
                    "MXNET_CKPT_EVERY_N_BATCHES=%d ignored under "
                    "fit(elastic=True): elastic snapshots every batch "
                    "(the manifest is the reshard rollback target)",
                    env_n)
            checkpoint_every_n_batches = 1

        if nan_policy is None:
            nan_policy = os.environ.get("MXNET_NAN_POLICY") or None
        if nan_policy is not None and nan_policy not in _NAN_POLICIES:
            raise MXNetError("nan_policy must be one of %s, got %r"
                             % (_NAN_POLICIES, nan_policy))
        if nan_check_period is None:
            nan_check_period = int(
                os.environ.get("MXNET_NAN_CHECK_PERIOD", "1") or 1)
        if nan_check_period < 1:
            raise MXNetError("nan_check_period must be >= 1, got %r"
                             % (nan_check_period,))
        if prefetch_to_device is None:
            prefetch_to_device = os.environ.get(
                "MXNET_DEVICE_PREFETCH", "0") not in ("0", "", "false")
        if nan_policy == "rollback" and checkpoint_prefix is None:
            raise MXNetError(
                "nan_policy='rollback' needs checkpoint_prefix to know "
                "what to roll back to")
        if anomaly_policy is None:
            anomaly_policy = os.environ.get("MXNET_ANOMALY_POLICY") or None
        if anomaly_policy is not None \
                and anomaly_policy not in _ANOMALY_POLICIES:
            raise MXNetError("anomaly_policy must be one of %s, got %r"
                             % (_ANOMALY_POLICIES, anomaly_policy))
        if anomaly_policy == "rollback" and checkpoint_prefix is None:
            raise MXNetError(
                "anomaly_policy='rollback' needs checkpoint_prefix to "
                "know what to roll back to")
        if audit_every_n_batches is None:
            audit_every_n_batches = int(os.environ.get(
                "MXNET_AUDIT_EVERY_N_BATCHES", "0") or 0) or None
        if audit_every_n_batches is not None \
                and audit_every_n_batches < 1:
            raise MXNetError(
                "audit_every_n_batches must be >= 1, got %r"
                % (audit_every_n_batches,))
        audit_policy = os.environ.get("MXNET_AUDIT_POLICY") or "raise"
        if audit_policy not in _AUDIT_POLICIES:
            raise MXNetError("MXNET_AUDIT_POLICY must be one of %s, "
                             "got %r" % (_AUDIT_POLICIES, audit_policy))
        if audit_every_n_batches is not None \
                and audit_policy == "rollback" \
                and checkpoint_prefix is None:
            raise MXNetError(
                "MXNET_AUDIT_POLICY='rollback' needs checkpoint_prefix "
                "to know what to roll back to")
        if resume not in (None, "auto"):
            raise MXNetError("resume must be None or 'auto', got %r"
                             % (resume,))
        if checkpoint_prefix is not None and checkpoint_period < 1:
            raise MXNetError("checkpoint_period must be >= 1, got %r"
                             % (checkpoint_period,))
        if checkpoint_every_n_batches is None:
            env_cadence = int(os.environ.get(
                "MXNET_CKPT_EVERY_N_BATCHES", "0") or 0) or None
            if env_cadence is not None and checkpoint_prefix is None:
                # a job-wide env cadence must not break fits that never
                # asked for checkpointing; only the EXPLICIT argument
                # hard-fails below
                self.logger.debug(
                    "MXNET_CKPT_EVERY_N_BATCHES=%d ignored: this fit "
                    "has no checkpoint_prefix", env_cadence)
            else:
                checkpoint_every_n_batches = env_cadence
        if checkpoint_every_n_batches is not None:
            if checkpoint_prefix is None:
                raise MXNetError(
                    "checkpoint_every_n_batches needs checkpoint_prefix")
            if checkpoint_every_n_batches < 1:
                raise MXNetError(
                    "checkpoint_every_n_batches must be >= 1, got %r"
                    % (checkpoint_every_n_batches,))
        resume_states = None
        resume_state = None  # mid-epoch TrainingState (exact resume)
        if resume == "auto":
            if checkpoint_prefix is None:
                raise MXNetError("resume='auto' needs checkpoint_prefix")
            from ..checkpoint import load_latest_state

            found = load_latest_state(checkpoint_prefix,
                                      logger=self.logger)
            if found is not None:
                _telemetry.inc("resilience.checkpoint.resumes")
                _telemetry.event("checkpoint.resume", epoch=found.epoch,
                                 nbatch=found.nbatch,
                                 prefix=checkpoint_prefix)
                begin_epoch = found.epoch
                arg_params, aux_params = \
                    found.arg_params, found.aux_params
                force_init = True
                if found.nbatch is None:
                    if found.states_path is not None \
                            and hasattr(self, "load_optimizer_states"):
                        resume_states = found.states_path
                    self.logger.info(
                        "resume='auto': restarting from checkpoint epoch "
                        "%d (%s)", found.epoch, checkpoint_prefix)
                else:
                    resume_state = found
                    self.logger.info(
                        "resume='auto': exact mid-epoch resume from "
                        "snapshot epoch %d batch %d (%s)", found.epoch,
                        found.nbatch, checkpoint_prefix)
            else:
                self.logger.info(
                    "resume='auto': no loadable checkpoint under %r; "
                    "starting from scratch", checkpoint_prefix)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if resume_states is not None:
            self.load_optimizer_states(resume_states)
        if resume_state is not None:
            # exact resume: optimizer states + update counts + RNG
            # streams (the iterator position is restored further down,
            # once the actual fit iterator — wrapper included — exists)
            if hasattr(self, "_restore_opt_snapshot"):
                self._restore_opt_snapshot(resume_state.states_bytes,
                                           resume_state.opt_counts)
            rng = resume_state.rng_state or {}
            if rng.get("global"):
                _random.set_state(rng["global"])
            ex = getattr(self, "_exec", None)
            if ex is not None and rng.get("exec_step") is not None:
                ex._rng_step = int(rng["exec_step"])
        if resume == "auto" and _compile_cache.enabled() \
                and hasattr(self, "warm_from_manifest"):
            # compile-once warm-up (docs/how_to/perf.md "Compile once"):
            # replay the manifest the previous run saved next to its
            # checkpoints, so every executable is pre-built — pure
            # persistent-cache loads — before the loop restarts.  AOT
            # only: nothing executes, exact-resume state is untouched.
            man = _compile_cache.load_manifest(
                _compile_cache.manifest_path(checkpoint_prefix))
            if man is not None:
                try:
                    self.warm_from_manifest(man)
                except Exception as e:  # noqa: broad-except — warm-up
                    # is an optimization; resume must proceed without it
                    self.logger.warning(
                        "compile_cache: warm-up manifest replay failed "
                        "(%s: %s); executables will compile lazily",
                        type(e).__name__, e)
        if hasattr(self, "_install_nan_guard"):
            # unconditional: a previous fit's guard must DISARM when this
            # fit runs without a policy (stale accumulated flags would
            # otherwise leak into a later guarded run)
            self._install_nan_guard(nan_policy)
        for pol_name, pol in (("nan_policy", nan_policy),
                              ("anomaly_policy", anomaly_policy)):
            if pol not in ("skip_batch", "rollback"):
                continue
            kv = getattr(self, "_kvstore", None)
            if kv is not None and getattr(kv, "num_workers", 1) > 1 \
                    and not getattr(kv, "in_graph_sync", False):
                # the NaN/anomaly check sees only this rank's
                # loss/grads (the anomaly z-score even judges against
                # rank-LOCAL history), and skipping update() skips this
                # rank's PS push — the other ranks still push, so sync
                # rounds shift one step out of phase (and 'rollback'
                # restores params on one rank only)
                self.logger.warning(
                    "%s=%r is rank-local: skipping a batch in "
                    "multi-worker sync training desynchronizes parameter-"
                    "server rounds across ranks; prefer %s='raise' "
                    "with resume='auto' for distributed runs",
                    pol_name, pol, pol_name)
        if validation_metric is None:
            validation_metric = eval_metric
        # materialize the validation metric ONCE so every epoch's score()
        # reuses one (device-wrapped) instance and its jit cache, instead
        # of re-creating + retracing per epoch
        validation_metric = _metric.as_device(_as_metric(validation_metric))
        eval_metric = _metric.as_device(_as_metric(eval_metric))

        # MXNET_BULK_TRAIN_STEPS=K dispatches K steps per XLA program
        # (Module.run_bulk lax.scan) — the training-loop spelling of the
        # reference's MXNET_EXEC_BULK_EXEC_TRAIN op bulking.  Metric
        # updates and batch callbacks still fire per batch (from the
        # scanned outputs); monitors need per-step observation, so a
        # monitor forces the classic path — as do the per-batch NaN guard
        # and the fit.batch fault point, which must see every step.
        bulk_k = max(1, int(os.environ.get("MXNET_BULK_TRAIN_STEPS", "1")))
        # the fit.preempt fault ("deliver SIGTERM at batch k") needs the
        # per-batch loop for deterministic batch-k delivery, like
        # fit.batch does
        # the sentinel's per-batch detectors (anomaly z-score, integrity
        # audit cadence, the fit.wedge fault) need the per-batch loop —
        # a scanned chunk has no batch boundaries to observe at
        use_bulk = bulk_k > 1 and monitor is None \
            and nan_policy is None and anomaly_policy is None \
            and audit_every_n_batches is None \
            and not _faults.armed("fit.batch") \
            and not _faults.armed("fit.preempt") \
            and not _faults.armed("fit.wedge") \
            and not elastic and hasattr(self, "run_bulk")
        if use_bulk and hasattr(self, "_full_step_eligible") \
                and not self._full_step_eligible():
            self.logger.warning(
                "MXNET_BULK_TRAIN_STEPS=%d has no effect: the fused step "
                "is not eligible (requires MXNET_FUSE_TRAIN_STEP=1, plain "
                "SGD, local/in-graph kvstore); training runs per batch",
                bulk_k)

        if _telemetry.enabled():
            # declare the resilience family at zero so a clean run's
            # snapshot still shows it (docs/observability.md)
            _telemetry.declare(*_RESILIENCE_COUNTERS)

        def _trip_nan_policy(epoch, nbatch, gated):
            """Apply ``nan_policy`` to a flagged batch.  ``gated``: the
            fused step already withheld the non-finite update in-graph."""
            _telemetry.inc("resilience.nan_batches", action=nan_policy)
            _telemetry.event("nan_batch", epoch=epoch, batch=nbatch,
                             action=nan_policy)
            _perfdebug.flight_dump("nan_trip", epoch=epoch, nbatch=nbatch,
                                   action=nan_policy)
            if nan_policy == "raise":
                raise MXNetError(
                    "NaN/Inf detected in loss/gradients at epoch %d "
                    "batch %d (nan_policy='raise')" % (epoch, nbatch))
            if nan_policy == "rollback":
                self.logger.warning(
                    "NaN/Inf at epoch %d batch %d: rolling back to the "
                    "last valid checkpoint", epoch, nbatch)
                self._rollback_to_checkpoint(checkpoint_prefix)
            elif gated:
                self.logger.warning(
                    "NaN/Inf at epoch %d batch %d: batch update withheld "
                    "in-graph (skip_batch)", epoch, nbatch)
            else:
                self.logger.warning(
                    "NaN/Inf at epoch %d batch %d: skipping batch",
                    epoch, nbatch)

        anomaly_detector = None
        anomaly_budget = None
        anomaly_consec = [0]  # consecutive skip/rollback trips
        if anomaly_policy is not None:
            anomaly_detector = _sentinel.AnomalyDetector()
            anomaly_budget = int(os.environ.get(
                "MXNET_ROLLBACK_BUDGET", "3") or 3)
            _telemetry.declare("reliability.anomalies")

        def _trip_anomaly(epoch, nbatch, value):
            """Apply ``anomaly_policy`` to a z-score-flagged batch whose
            update was WITHHELD (the grad-norm read happens before
            ``update()``)."""
            _telemetry.inc("reliability.anomalies", action=anomaly_policy)
            _telemetry.event("reliability.anomaly", epoch=epoch,
                             batch=nbatch, action=anomaly_policy,
                             grad_norm=value)
            _perfdebug.flight_dump("anomaly", epoch=epoch, nbatch=nbatch,
                                   action=anomaly_policy, grad_norm=value)
            if anomaly_policy == "raise":
                raise MXNetError(
                    "gradient-norm anomaly (%.4g) at epoch %d batch %d "
                    "(anomaly_policy='raise')" % (value, epoch, nbatch))
            anomaly_consec[0] += 1
            if anomaly_consec[0] > anomaly_budget:
                raise _sentinel.AnomalyBudgetExhausted(
                    "anomaly_policy=%r tripped on %d consecutive batches "
                    "(MXNET_ROLLBACK_BUDGET=%d): the spike is not "
                    "transient — refusing to %s forever"
                    % (anomaly_policy, anomaly_consec[0], anomaly_budget,
                       anomaly_policy))
            if anomaly_policy == "rollback":
                self.logger.warning(
                    "gradient-norm anomaly (%.4g) at epoch %d batch %d: "
                    "rolling back to the last valid checkpoint and "
                    "skipping the batch (%d/%d consecutive)",
                    value, epoch, nbatch, anomaly_consec[0],
                    anomaly_budget)
                self._rollback_to_checkpoint(checkpoint_prefix)
            else:
                self.logger.warning(
                    "gradient-norm anomaly (%.4g) at epoch %d batch %d: "
                    "skipping batch (%d/%d consecutive)",
                    value, epoch, nbatch, anomaly_consec[0],
                    anomaly_budget)

        # device-side double-buffered prefetch: a background thread runs
        # each batch's host→device copy (honoring the module's sharding
        # via _device_put_batch) so H2D overlaps the previous step's
        # compute — the device-level completion of PrefetchingIter's
        # host-decode overlap (iter_prefetcher.h analog)
        fit_data = train_data
        if prefetch_to_device and hasattr(self, "_device_put_batch") \
                and not getattr(self, "_dist_dp", False):
            from ..io import DevicePrefetchIter

            fit_data = DevicePrefetchIter(train_data,
                                          placer=self._device_put_batch)
        owns_iter = fit_data is not train_data
        # exact mid-epoch resume: the iterator position restores onto the
        # iterator fit actually drives (the prefetch wrapper when owned —
        # its restore drains the queue and rewinds the inner iterator)
        resume_nbatch = None
        resume_metric_state = None
        if resume_state is not None and resume_state.nbatch is not None:
            if resume_state.iter_state is not None:
                try:
                    fit_data.load_state_dict(_adapt_iter_state(
                        resume_state.iter_state, fit_data))
                    resume_nbatch = resume_state.nbatch
                    resume_metric_state = resume_state.metric_state
                except Exception as e:  # noqa: broad-except — ANY
                    # restore failure (unsupported iterator, a snapshot
                    # from a different iterator type raising KeyError,
                    # shape mismatch) must degrade to epoch-boundary
                    # resume, never abort a fit whose params snapshot
                    # loaded fine
                    self.logger.warning(
                        "resume: could not restore the iterator position "
                        "(%s: %s); restarting epoch %d from batch 0 — "
                        "data from the partial epoch will replay",
                        type(e).__name__, e, resume_state.epoch)
            else:
                self.logger.warning(
                    "resume: snapshot carries no iterator state; "
                    "restarting epoch %d from batch 0 — data from the "
                    "partial epoch will replay", resume_state.epoch)
        elastic_run = None
        if elastic:
            from ..elastic import ElasticFitRun

            kv = getattr(self, "_kvstore", None)
            if kv is None or not hasattr(kv, "reshard_sync"):
                raise MXNetError(
                    "fit(elastic=True) needs a dist_* kvstore (got %r): "
                    "elastic membership lives on the KVStore coordinator"
                    % (kvstore if kv is None else kv.type))
            elastic_run = ElasticFitRun(self, kv, checkpoint_prefix,
                                        fit_data, self.logger)
            _telemetry.declare("elastic.resharded.count",
                               "elastic.stale_epoch.count")
        writer = None
        if checkpoint_every_n_batches is not None:
            from ..checkpoint import AsyncSnapshotWriter

            # elastic snapshots are the reshard rollback target: they
            # must exist deterministically at every committed boundary,
            # so the writer is PINNED inline (the async writer drops
            # cadence snapshots when busy, which would make the rollback
            # generation timing-dependent and break replay bit-identity)
            # — an explicit MXNET_CKPT_ASYNC=1 is ignored with a warning,
            # the same treatment MXNET_CKPT_EVERY_N_BATCHES gets
            ckpt_async = os.environ.get(
                "MXNET_CKPT_ASYNC", "0" if elastic else "1") \
                not in ("0", "", "false")
            if elastic and ckpt_async:
                self.logger.warning(
                    "MXNET_CKPT_ASYNC=1 ignored under fit(elastic=True): "
                    "elastic snapshots are the reshard rollback target "
                    "and must land inline at every committed boundary")
                ckpt_async = False
            writer = AsyncSnapshotWriter(checkpoint_prefix,
                                         logger=self.logger,
                                         sync=not ckpt_async)
        guard = _PreemptGuard()
        run = _FitRun(checkpoint_prefix, checkpoint_every_n_batches,
                      writer, guard, self.logger, elastic=elastic_run)
        # visible to _rollback_to_checkpoint: a rollback must quiesce
        # the writer before discarding post-rollback snapshots
        self._active_ckpt_writer = writer
        watchdog = None
        if _sentinel.watchdog_enabled():
            # the hang watchdog arms for exactly this fit's duration;
            # start() runs HERE so the injection target is this thread
            watchdog = _sentinel.Watchdog(logger=self.logger)
        try:
            # graceful preemption is tied to checkpointing: a fit that
            # never asked for a checkpoint_prefix keeps the process's
            # own SIGTERM/SIGINT semantics (Ctrl-C still interrupts);
            # the SIGQUIT dump-on-demand probe is unconditional
            with _sigquit_dump(self.logger), \
                    _preempt_signals(guard, self.logger,
                                     enable=checkpoint_prefix is not None):
                if watchdog is not None:
                    watchdog.start()
                try:
                    if elastic_run is not None:
                        # initial rendezvous: adopt the membership epoch
                        # and world, shard the data service — and, for a
                        # mid-job JOINER, rehydrate from the running
                        # job's newest snapshot generation
                        begin_epoch, resume_nbatch, resume_metric_state \
                            = elastic_run.sync(
                                (begin_epoch, resume_nbatch,
                                 resume_metric_state))
                    while True:
                        try:
                            self._fit_epochs(
                                fit_data, eval_data, eval_metric,
                                validation_metric, epoch_end_callback,
                                batch_end_callback, eval_end_callback,
                                eval_batch_end_callback, monitor,
                                begin_epoch, num_epoch, checkpoint_prefix,
                                checkpoint_period, nan_policy,
                                nan_check_period, use_bulk, bulk_k,
                                _trip_nan_policy, owns_iter, run=run,
                                resume_nbatch=resume_nbatch,
                                resume_metric_state=resume_metric_state,
                                anomaly_policy=anomaly_policy,
                                anomaly_detector=anomaly_detector,
                                anomaly_consec=anomaly_consec,
                                trip_anomaly=_trip_anomaly,
                                audit_every=audit_every_n_batches,
                                audit_policy=audit_policy)
                            break
                        except _ELASTIC_RESYNC as e:
                            if elastic_run is None:
                                raise
                            # membership moved: quiesce is NOW (we are at
                            # a batch boundary, or the update that raised
                            # StaleEpoch never landed) — run the reshard
                            # cycle and re-enter the loop in-process
                            self.logger.info(
                                "elastic: quiescing for reshard (%s)", e)
                            begin_epoch, resume_nbatch, \
                                resume_metric_state = elastic_run.sync(
                                    (begin_epoch, resume_nbatch,
                                     resume_metric_state))
                except Exception as e:
                    # crash flight record: preemption, NaN trips and
                    # watchdog hangs dumped at their own sites already
                    # (with richer context); anything else dying out of
                    # fit gets the generic crash dump before the
                    # exception escapes
                    from ..checkpoint import TrainingPreempted

                    if not isinstance(e, (TrainingPreempted,
                                          _sentinel.TrainingWedged)):
                        _perfdebug.flight_dump(
                            "crash",
                            error="%s: %s" % (type(e).__name__, e))
                    if elastic_run is not None:
                        # ANY exit — preemption, NaN raise, a crashed
                        # callback — leaves the job: announce it so the
                        # survivors reshard at their next batch boundary
                        # instead of stalling a full heartbeat deadline
                        # in a sync round this rank will never join
                        # (best-effort; a severed transport falls back
                        # to heartbeat-death eviction)
                        elastic_run.leave()
                    raise
            if writer is not None:
                # clean-path close surfaces a failed background write as
                # an error instead of silently training un-checkpointed
                writer.close()
            if owns_iter:
                # restore fit's postcondition (train_data left reset)
                # only after the producer threads are joined — the
                # wrapper's own reset would re-arm them, racing for the
                # user's first post-fit batch
                fit_data.close()
                train_data.reset()
        finally:
            self._active_ckpt_writer = None
            if watchdog is not None:
                # the monitor thread must never outlive its fit (a
                # stale watchdog would inject into an innocent caller)
                watchdog.stop()
            if writer is not None:
                try:
                    writer.close()
                except Exception as e:  # noqa: broad-except — the clean
                    # path above already surfaced writer errors; here we
                    # must not mask the in-flight exception (preemption,
                    # NaN raise) with a checkpoint-write failure
                    self.logger.warning(
                        "async checkpoint writer close: %s", e)
            if owns_iter:
                fit_data.close()

    def _fit_epochs(self, fit_data, eval_data, eval_metric,
                    validation_metric, epoch_end_callback,
                    batch_end_callback, eval_end_callback,
                    eval_batch_end_callback, monitor, begin_epoch,
                    num_epoch, checkpoint_prefix, checkpoint_period,
                    nan_policy, nan_check_period, use_bulk, bulk_k,
                    _trip_nan_policy, owns_iter=False, run=None,
                    resume_nbatch=None, resume_metric_state=None,
                    anomaly_policy=None, anomaly_detector=None,
                    anomaly_consec=None, trip_anomaly=None,
                    audit_every=None, audit_policy="raise"):
        """The epoch/batch loop body of :meth:`fit` (split out so the
        device-prefetch wrapper can be closed deterministically).

        ``run`` is the per-fit :class:`_FitRun` (snapshot cadence +
        preemption drain); ``resume_nbatch``/``resume_metric_state``
        position the FIRST epoch mid-stream for an exact mid-epoch
        resume — the iterator was already rewound by :meth:`fit`."""
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            start_nbatch = -1
            if resume_nbatch is not None and epoch == begin_epoch:
                # continue the interrupted epoch: batch numbering picks
                # up after the last completed batch (cadences — NaN
                # window, snapshots, callbacks — stay aligned with an
                # uninterrupted run) and the metric resumes its sums
                start_nbatch = resume_nbatch
                if resume_metric_state is not None:
                    eval_metric.set_state(resume_metric_state)
            if use_bulk:
                nbatch = start_nbatch
                chunk = []
                device_out = isinstance(eval_metric, _metric.DeviceMetric)

                def _flush(chunk, nbatch):
                    # one span per fused chunk — the bulk-mode analogue
                    # of the per-batch span below
                    bsp = _tracing.start_span("fit.batch", stack=False,
                                              epoch=epoch, k=len(chunk))
                    with _telemetry.phase("bulk_step"):
                        # device metrics consume the stacked outputs
                        # without the (K, ...) host transfer
                        outs = self.run_bulk(
                            chunk, return_outputs="device" if device_out
                            else True)
                    for i, b in enumerate(chunk):
                        nbatch += 1
                        _telemetry.inc("fit.batches")
                        eval_metric.update(b.label, [o[i] for o in outs])
                        if batch_end_callback is not None:
                            bp = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                               eval_metric=eval_metric,
                                               locals=locals())
                            for callback in _as_list(batch_end_callback):
                                callback(bp)
                    bsp.end("ok", nbatch=nbatch)
                    return nbatch

                train_iter = iter(fit_data)
                while True:
                    with _telemetry.phase("data"):
                        data_batch = next(train_iter, _FIT_END)
                    if data_batch is _FIT_END:
                        break
                    chunk.append(data_batch)
                    if len(chunk) == bulk_k:
                        nbatch = _flush(chunk, nbatch)
                        chunk = []
                        if run is not None:
                            # bulk mode snapshots/preempts at chunk
                            # boundaries only — params mid-chunk reflect
                            # later batches' updates (the scan carries
                            # them), so a mid-chunk capture could never
                            # resume exactly
                            run.after_batch(self, epoch, nbatch,
                                            fit_data, eval_metric)
                if chunk:
                    nbatch = _flush(chunk, nbatch)
                    if run is not None:
                        run.after_batch(self, epoch, nbatch, fit_data,
                                        eval_metric)
            else:
                train_iter = iter(fit_data)
                nbatch = start_nbatch
                # True while EVERY unread batch since the last flag read
                # was a staged fused step (whose in-graph gate withheld
                # non-finite updates) — a two-phase batch in the window
                # means a poisoned update may have landed, and the trip
                # log must not claim otherwise
                window_all_staged = True
                while True:
                    # the step phases (data wait / forward+backward /
                    # optimizer+kvstore sync / metric dispatch) land in
                    # telemetry's fit.phase_seconds and, when the profiler
                    # runs, as chrome-trace spans.  JAX dispatch is async:
                    # device compute time surfaces in the first BLOCKING
                    # phase — with device metrics and the in-graph NaN
                    # guard that is the explicit `sync` phase (metric
                    # reads, guard-flag reads), no longer `metric`.
                    with _telemetry.phase("data"):
                        data_batch = next(train_iter, _FIT_END)
                    if data_batch is _FIT_END:
                        break
                    nbatch += 1
                    # per-batch trace span (data wait excluded — it sits
                    # before the batch starts); disabled-mode cost is two
                    # no-op calls, inside the fit overhead pin
                    bsp = _tracing.start_span("fit.batch", stack=False,
                                              epoch=epoch, nbatch=nbatch)
                    if _faults.should_fire("fit.preempt"):
                        # deterministic preemption: a REAL SIGTERM to
                        # this process — the handler sets the drain flag
                        # and the bottom-of-batch check does the rest,
                        # exactly like a pod eviction would
                        self.logger.warning(
                            "fault 'fit.preempt': delivering SIGTERM at "
                            "epoch %d batch %d", epoch, nbatch)
                        os.kill(os.getpid(), _signal.SIGTERM)
                    if monitor is not None:
                        monitor.tic()
                    with _telemetry.phase("forward_backward"):
                        self.forward_backward(data_batch)
                    if _faults.should_fire("fit.batch"):
                        self.logger.warning(
                            "fault 'fit.batch': poisoning gradients with "
                            "NaN at epoch %d batch %d", epoch, nbatch)
                        self._poison_gradients_nan()
                    if _faults.should_fire("fit.wedge"):
                        self.logger.warning(
                            "fault 'fit.wedge': wedging the step at "
                            "epoch %d batch %d (the hang watchdog must "
                            "trip)", epoch, nbatch)
                        _sentinel.wedge_sleep()
                    nan_detected = False
                    nan_action = None
                    anomaly_detected = False
                    anomaly_action = None
                    staged = bool(getattr(self, "_pending_full", False))
                    window_all_staged = window_all_staged and staged
                    check_nan = nan_policy is not None and \
                        (nbatch + 1) % nan_check_period == 0
                    # guard cadence: the two-phase path checks BEFORE the
                    # update (exact skip); a staged fused step runs first
                    # — its in-graph gate already withheld any non-finite
                    # update — and the accumulated flag is read after.
                    # Either read is one scalar (or a device-side
                    # reduction after an out-of-graph gradient mutation),
                    # never per-array host pulls.
                    tripped = check_nan and not staged \
                        and self._batch_has_nonfinite()
                    anomaly_tripped = False
                    anomaly_value = None
                    if not tripped and anomaly_detector is not None:
                        # grad-norm read BEFORE the update so a
                        # skip/rollback trip really withholds the
                        # poisoned step; a staged fused step is
                        # materialized two-phase first (its gradients
                        # must be inspectable — the monitor trade)
                        with _telemetry.phase("sync"):
                            anomaly_value = self._batch_grad_norm()
                        anomaly_tripped = anomaly_detector.observe(
                            anomaly_value)
                        staged = bool(getattr(self, "_pending_full",
                                              False))
                    if not tripped and not anomaly_tripped:
                        with _telemetry.phase("update"):
                            self.update()
                        if check_nan and staged:
                            tripped = self._batch_has_nonfinite()
                    if tripped:
                        nan_detected = True
                        nan_action = nan_policy
                        _trip_nan_policy(epoch, nbatch,
                                         gated=window_all_staged)
                    elif anomaly_tripped:
                        anomaly_detected = True
                        anomaly_action = anomaly_policy
                        trip_anomaly(epoch, nbatch, anomaly_value)
                    else:
                        if anomaly_consec is not None:
                            anomaly_consec[0] = 0  # clean batch: budget
                            # counts CONSECUTIVE trips only
                        with _telemetry.phase("metric"):
                            self.update_metric(eval_metric,
                                               data_batch.label)
                    if check_nan:
                        window_all_staged = True  # flag consumed: new window
                    _telemetry.inc("fit.batches")
                    bsp.end("retry" if (nan_detected or anomaly_detected)
                            else "ok")
                    if audit_every is not None and \
                            (nbatch + 1) % audit_every == 0:
                        audit = getattr(self, "_run_integrity_audit",
                                        None)
                        if audit is not None:
                            with _telemetry.phase("audit"):
                                audit(audit_policy, checkpoint_prefix,
                                      epoch, nbatch)
                    if monitor is not None:
                        monitor.toc_print()
                    if batch_end_callback is not None:
                        batch_end_param = BatchEndParam(
                            epoch=epoch, nbatch=nbatch,
                            eval_metric=eval_metric, locals=locals(),
                            nan_detected=nan_detected,
                            nan_action=nan_action,
                            anomaly_detected=anomaly_detected,
                            anomaly_action=anomaly_action)
                        for callback in _as_list(batch_end_callback):
                            callback(batch_end_param)
                    if run is not None:
                        # cadence snapshot + pending-preemption drain;
                        # the guard drain mirrors the epoch-boundary one
                        # so a poisoned window never checkpoints silently
                        run.after_batch(
                            self, epoch, nbatch, fit_data, eval_metric,
                            drain_guard=lambda e=epoch, b=nbatch,
                            g=window_all_staged: self._drain_nan_window(
                                nan_policy, nan_check_period, e, b, g,
                                _trip_nan_policy),
                            # a NaN- or anomaly-tripped batch's update
                            # never landed (skipped or rolled back): it
                            # must not enter the elastic data ledger as
                            # trained
                            data_batch=None
                            if (nan_detected or anomaly_detected)
                            else data_batch)
                # epoch-boundary drain: with nan_check_period > 1 the
                # last window may not have been read yet — a NaN epoch
                # must not survive into checkpoint/eval unflagged
                if nan_policy is not None and nbatch >= 0 and \
                        (nbatch + 1) % nan_check_period != 0 and \
                        self._batch_has_nonfinite():
                    _trip_nan_policy(epoch, nbatch,
                                     gated=window_all_staged)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            toc = time.time()
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, (toc - tic))
            _telemetry.inc("fit.epochs")
            _telemetry.set_gauge("fit.epoch_seconds", toc - tic)
            _telemetry.sample_memory()

            arg_params_, aux_params_ = self.get_params()
            self.set_params(arg_params_, aux_params_)
            if checkpoint_prefix is not None and \
                    ((epoch + 1) % checkpoint_period == 0
                     or epoch + 1 == num_epoch) and \
                    (run is None or run.elastic is None
                     or run.elastic.is_leader()):
                # elastic fits share one prefix: the membership leader
                # owns the epoch checkpoints (like the snapshot cadence)
                with _telemetry.phase("checkpoint"):
                    self._save_fit_checkpoint(checkpoint_prefix, epoch + 1,
                                              arg_params_, aux_params_)
            if epoch_end_callback is not None:
                for callback in _as_list(epoch_end_callback):
                    callback(epoch, self.symbol, arg_params_, aux_params_)
                # user epoch-end work (uploads, evals) emits no phases:
                # it is slow, not wedged — tick the watchdog
                _sentinel.note_progress()
            if eval_data:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
            if epoch + 1 < num_epoch or not owns_iter:
                # an owned prefetch wrapper skips the FINAL reset: it
                # would re-arm the producer thread, which could consume
                # the user's first post-fit batch before close() lands
                fit_data.reset()
            if run is not None and run.guard is not None and \
                    run.guard.requested is not None:
                # a signal that landed during epoch-end processing
                # (checkpoint save, callbacks, the eval pass) must not
                # be swallowed: the epoch is complete, so the drain
                # point is the epoch BOUNDARY — an epoch checkpoint,
                # not a mid-epoch snapshot of the already-reset iterator
                already_saved = checkpoint_prefix is not None and \
                    ((epoch + 1) % checkpoint_period == 0
                     or epoch + 1 == num_epoch)
                run.epoch_end_preempt(self, epoch, already_saved)

    # -- resilience helpers (docs/resilience.md) --------------------------
    def _drain_nan_window(self, nan_policy, nan_check_period, epoch,
                          nbatch, gated, trip):
        """Preemption-time NaN-guard drain: identical semantics to the
        epoch-boundary drain — a partial read window is flushed so a
        poisoned batch never slips into the final checkpoint unflagged."""
        if nan_policy is not None and nbatch >= 0 and \
                (nbatch + 1) % nan_check_period != 0 and \
                self._batch_has_nonfinite():
            trip(epoch, nbatch, gated=gated)

    def _guard_exec(self):
        """The executor whose gradients the NaN guard inspects: this
        module's, or the active bucket's for BucketingModule."""
        ex = getattr(self, "_exec", None)
        if ex is None:
            ex = getattr(getattr(self, "_curr_module", None), "_exec", None)
        return ex

    def _batch_has_nonfinite(self):
        """True when any output (loss) or parameter gradient of the batch
        just computed contains NaN/Inf.  Device-side either way: the
        executor's accumulated in-graph guard flag when available (ONE
        scalar transfer — the reduction already ran inside the step), else
        one jitted logical-or reduction over the live outputs+grads (the
        path after an out-of-graph gradient mutation, and for modules
        without the fused guard).  Either read lands in the telemetry
        ``sync`` phase."""
        ex = self._guard_exec()
        with _telemetry.phase("sync"):
            if ex is not None and getattr(ex, "_nan_acc", None) is not None \
                    and not getattr(ex, "_nan_stale", False):
                return ex.consume_nan_flag()
            if ex is not None:
                # a stale accumulator predates the mutation that made it
                # stale — discard it and reduce over the arrays as-is
                ex._nan_acc = None
                ex._nan_stale = False
            arrays = [o._jx for o in self.get_outputs()
                      if hasattr(o, "_jx")]
            if ex is not None:
                arrays += [g._jx for g in ex.grad_dict.values()
                           if g is not None]
            from ..executor import any_nonfinite

            try:
                return any_nonfinite(arrays)
            except ValueError:
                # mixed-device arrays (group2ctx placement) cannot share
                # one jit — fall back to per-array host checks
                for a in arrays:
                    v = np.asarray(a)  # host-sync: ok — group2ctx fallback
                    if v.dtype.kind == "f" and not np.isfinite(v).all():
                        return True
                return False

    def _batch_grad_norm(self):
        """Global L2 norm of the batch's parameter gradients as a python
        float — the statistic ``anomaly_policy`` z-scores.  One jitted
        sum-of-squares reduction + a single scalar transfer
        (``executor.global_norm``); a staged fused step is materialized
        first so the gradients exist to inspect."""
        mat = getattr(self, "_materialize_pending", None)
        if mat is not None:
            mat()
        ex = self._guard_exec()
        if ex is None:
            return 0.0
        from ..executor import global_norm

        return global_norm([g._jx for g in ex.grad_dict.values()
                            if g is not None])

    def _poison_gradients_nan(self):
        """fault 'fit.batch': overwrite the first parameter gradient with
        NaN — the observable state of a corrupt reduction/overflow."""
        mat = getattr(self, "_materialize_pending", None)
        if mat is not None:
            mat()  # a staged fused step would recompute (unpoison) grads
        ex = self._guard_exec()
        if ex is None:
            raise MXNetError("fault 'fit.batch' armed but this module "
                             "exposes no gradient arrays")
        for g in ex.grad_dict.values():
            if g is not None:
                g[:] = np.nan
                # the in-graph guard flag predates this mutation: force
                # the next check onto the live-array reduction
                ex._nan_stale = True
                return
        raise MXNetError("fault 'fit.batch' armed but no gradients bound")

    def _rollback_to_checkpoint(self, prefix):
        """nan_policy='rollback': restore params from the newest valid
        checkpoint under ``prefix``."""
        from ..model import load_latest_checkpoint

        found = load_latest_checkpoint(prefix, logger=self.logger)
        if found is None:
            raise MXNetError(
                "nan_policy='rollback': no valid checkpoint under prefix "
                "%r to roll back to" % prefix)
        epoch, _sym, arg_params, aux_params = found
        self.set_params(arg_params, aux_params, force_init=True)
        # restore optimizer state too: post-divergence moments (inflated
        # by the huge pre-NaN gradients) applied to rolled-back weights
        # would immediately re-diverge
        states = "%s-%04d.states" % (prefix, epoch)
        if os.path.exists(states) and hasattr(self,
                                              "load_optimizer_states"):
            self.load_optimizer_states(states)
        else:
            self.logger.warning(
                "rollback: no optimizer state snapshot (%s); keeping "
                "current optimizer moments with epoch-%d parameters",
                states, epoch)
        # mid-epoch snapshots NEWER than the rollback point describe the
        # abandoned (diverging) trajectory — left in place, a later
        # resume='auto' would prefer them and resurrect exactly the
        # state this rollback just discarded.  Quiesce the async writer
        # FIRST: an in-flight pre-NaN snapshot committing after the
        # discard would re-poison the manifest
        from ..checkpoint import discard_snapshots_from

        writer = getattr(self, "_active_ckpt_writer", None)
        if writer is not None:
            try:
                writer.drain()
            except Exception as e:  # noqa: broad-except — a failed
                # background write must not abort the rollback itself
                self.logger.warning(
                    "rollback: async snapshot writer error ignored "
                    "while quiescing (%s)", e)
        discard_snapshots_from(prefix, epoch, logger=self.logger)
        self.logger.info("rolled back parameters to checkpoint epoch %d",
                         epoch)
        _telemetry.inc("resilience.rollbacks")
        _telemetry.event("rollback", to_epoch=epoch, prefix=prefix)
        return epoch

    def _save_fit_checkpoint(self, prefix, epoch, arg_params, aux_params):
        """Per-epoch atomic checkpoint from inside fit (params + optimizer
        states when the module supports them + manifest)."""
        _telemetry.inc("resilience.checkpoint.saves")
        if hasattr(self, "save_checkpoint"):
            self.save_checkpoint(
                prefix, epoch,
                save_optimizer_states=self.optimizer_initialized)
        else:
            from ..model import save_checkpoint as _save_ckpt

            _save_ckpt(prefix, epoch, self.symbol, arg_params, aux_params)
        if _compile_cache.recording():
            # the warm-up manifest rides the checkpoint cadence: a
            # restart replays it to pre-build every executable this fit
            # compiled (no-op when the entry set is unchanged)
            _compile_cache.save_manifest_if_changed(
                _compile_cache.manifest_path(prefix))

    # -- properties / abstract --------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        from ..ndarray import save

        save(fname, save_dict)

    def load_params(self, fname):
        from ..ndarray import load

        save_dict = load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def install_monitor(self, mon):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
