"""AttrScope — scoped symbol attributes (reference ``python/mxnet/attribute.py``).

Carries ``ctx_group`` for model parallelism (reference
``example/model-parallel-lstm/lstm.py:48-99``) plus arbitrary ``__key__``
attributes like lr_mult/wd_mult consumed by the optimizer.
"""

from __future__ import annotations

import threading

__all__ = ["AttrScope"]


class AttrScope:
    _tls = threading.local()

    def __init__(self, **kwargs):
        self._attr = {str(k): str(v) for k, v in kwargs.items()}

    def get(self, attr):
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        stack = AttrScope._stack()
        merged = dict(stack[-1]._attr)
        merged.update(self._attr)
        new = AttrScope(**merged)
        stack.append(new)
        return new

    def __exit__(self, *exc):
        AttrScope._stack().pop()

    @staticmethod
    def _stack():
        if not hasattr(AttrScope._tls, "stack"):
            AttrScope._tls.stack = [AttrScope()]
        return AttrScope._tls.stack

    @staticmethod
    def current():
        return AttrScope._stack()[-1]
