"""Preemption-tolerant checkpointing: async batch-granular snapshots.

TPU pods preempt.  The TensorFlow paper (Abadi et al., 2016, §4.3)
treats checkpoint/restore as *the* fault-tolerance primitive of a
dataflow system, and the property that makes preemption a non-event is
that snapshots are (a) fine-grained — losing at most a few batches —
and (b) cheap enough to take constantly.  This module supplies both for
``Module.fit`` (docs/resilience.md "Preemption & exact resume"):

* **Capture is device-side and async.**  A snapshot starts as
  ``NDArray.copy()`` of every parameter / aux / optimizer-state array —
  one dispatched device-to-device copy each, no host sync on the
  training loop.  The host-owned smalls (iterator cursor, RNG state,
  metric sums, optimizer update counts) are captured synchronously;
  they are dict-sized.
* **Serialization is one background writer thread.**  The writer pulls
  the captured snapshot, performs the device→host transfer *there*, and
  writes through the ``base.atomic_write`` temp+fsync+rename protocol
  with the manifest updated LAST — a crash at any byte leaves the
  previous generation fully loadable.  Back-pressure is strict: at most
  ONE snapshot may be in flight (queued or writing); a cadence tick
  that lands while the writer is busy is *dropped* and counted
  (``resilience.checkpoint.async_dropped``) rather than queued — two
  in-flight snapshots would double the pinned device copies.
* **Payloads are sha256-verified.**  Every generation records the
  digest of its params/states files in the manifest; resume re-hashes
  before loading and falls back to the previous generation on mismatch
  (``resilience.checkpoint.corrupt_skipped``).
* **Retention is generational.**  ``MXNET_CKPT_KEEP_LAST`` (default 3)
  bounds the on-disk snapshot generations; GC removes a generation's
  manifest entry FIRST, then its payload files, so a crash mid-GC can
  orphan a payload (harmless, swept next GC) but never leave a
  manifest entry pointing at removed bytes.

Telemetry family: ``resilience.checkpoint.async_write_seconds`` /
``resilience.checkpoint.queue_wait_seconds`` (histograms — write
duration, and how long a submitted snapshot waited for the writer
thread), ``resilience.checkpoint.async_inflight`` (gauge),
``resilience.checkpoint.async_dropped`` / ``.corrupt_skipped`` /
``.pruned`` (counters) — see docs/observability.md.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time

from . import telemetry as _telemetry
from . import tracing as _tracing
from .base import MXNetError, atomic_write, atomic_write_bytes

__all__ = ["TrainingPreempted", "Snapshot", "TrainingState",
           "AsyncSnapshotWriter", "snapshot_path", "write_snapshot",
           "gc_snapshots", "discard_snapshots_from", "load_latest_state",
           "latest_generation_summary", "keep_last_default"]

#: iterator states larger than this (JSON bytes) move to a per-
#: generation sidecar file instead of the manifest — a shuffled
#: ImageIter's full permutation is O(dataset) and must not be rewritten
#: into the manifest (under its lock) on every cadence tick
ITER_STATE_INLINE_BYTES = 16384


class TrainingPreempted(MXNetError):
    """``Module.fit`` was preempted (SIGTERM/SIGINT) and drained
    gracefully: the in-flight batch finished, accumulators were flushed,
    and a final checkpoint was written.  ``checkpoint_path`` names it
    (None when fit ran without ``checkpoint_prefix``); ``epoch`` /
    ``nbatch`` locate the last completed batch."""

    def __init__(self, msg, checkpoint_path=None, epoch=None, nbatch=None,
                 signum=None):
        super().__init__(msg)
        self.checkpoint_path = checkpoint_path
        self.epoch = epoch
        self.nbatch = nbatch
        self.signum = signum


class Snapshot:
    """One captured mid-epoch training state, pre-serialization.

    ``arg_params``/``aux_params`` map name → NDArray *device copies*;
    ``opt_states`` is the updater's ``{index: state}`` tree of device
    copies (or None when the module has no local updater).  The rest are
    small JSON-able host dicts captured synchronously."""

    __slots__ = ("epoch", "nbatch", "arg_params", "aux_params",
                 "opt_states", "opt_counts", "rng_state", "metric_state",
                 "iter_state", "mesh_info")

    def __init__(self, epoch, nbatch, arg_params, aux_params,
                 opt_states=None, opt_counts=None, rng_state=None,
                 metric_state=None, iter_state=None, mesh_info=None):
        self.epoch = int(epoch)
        self.nbatch = int(nbatch)
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.opt_states = opt_states
        self.opt_counts = opt_counts
        self.rng_state = rng_state
        self.metric_state = metric_state
        self.iter_state = iter_state
        #: sharding descriptor from ``Module._snapshot_mesh_info`` (None
        #: = single payload file): ``{"num_shards": W, "axis": ...,
        #: "mesh_axes": [...], "mesh_shape": [...]}`` — the generation
        #: is then written as W per-shard payload files stitched by the
        #: manifest (docs/how_to/multi_devices.md "Sharded snapshots")
        self.mesh_info = mesh_info


class TrainingState:
    """What resume recovers: the richest verified state under a prefix.

    ``nbatch`` is None for an epoch-boundary checkpoint (resume restarts
    epoch ``epoch`` from batch 0, the pre-existing behavior) and the
    0-based index of the last completed batch for a mid-epoch snapshot
    (resume continues at ``nbatch + 1`` of epoch ``epoch``)."""

    __slots__ = ("epoch", "nbatch", "arg_params", "aux_params",
                 "states_path", "states_bytes", "rng_state",
                 "metric_state", "iter_state", "opt_counts", "path")

    def __init__(self, epoch, nbatch, arg_params, aux_params,
                 states_path=None, states_bytes=None, rng_state=None,
                 metric_state=None, iter_state=None, opt_counts=None,
                 path=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.states_path = states_path
        self.states_bytes = states_bytes
        self.rng_state = rng_state
        self.metric_state = metric_state
        self.iter_state = iter_state
        self.opt_counts = opt_counts
        self.path = path


def keep_last_default():
    """Snapshot generations kept on disk (``MXNET_CKPT_KEEP_LAST``)."""
    return int(os.environ.get("MXNET_CKPT_KEEP_LAST", "3") or 3)


def snapshot_path(prefix, epoch, nbatch, kind="params"):
    """``<prefix>-snap-EEEE-BBBBBB.params`` — distinct from the epoch
    checkpoint namespace (``<prefix>-EEEE.params``), so the epoch scan
    in ``model.list_checkpoints`` never confuses a mid-epoch snapshot
    for a completed epoch."""
    return "%s-snap-%04d-%06d.%s" % (prefix, epoch, nbatch, kind)


def write_snapshot(prefix, snap, logger=logging, keep_last=None):
    """Serialize ``snap`` crash-safely under ``prefix`` (blocking; the
    device→host transfer happens inside).  Payloads first, each atomic;
    the manifest entry (with payload sha256s) is committed LAST; then
    retention GC runs.  Returns the params path."""
    from . import ndarray as nd
    from . import model as _model

    t0 = time.perf_counter()
    csp = _tracing.start_span("checkpoint.write", stack=False,
                              epoch=snap.epoch, nbatch=snap.nbatch)
    mesh_info = getattr(snap, "mesh_info", None)
    if mesh_info:
        params_path, entry = _write_sharded_payloads(prefix, snap,
                                                     mesh_info)
    else:
        params_path = snapshot_path(prefix, snap.epoch, snap.nbatch,
                                    "params")
        save_dict = _snapshot_save_dict(snap)
        # durable=False: snapshot writes stay atomic against PROCESS death
        # (the preemption threat model) but skip the fsync stalls; the
        # fully-durable epoch checkpoint bounds power-loss exposure
        atomic_write(params_path, lambda tmp: nd.save(tmp, save_dict),
                     fault_point="checkpoint.write", durable=False)
        entry = {
            "epoch": snap.epoch, "nbatch": snap.nbatch,
            "params": os.path.basename(params_path),
            "sha256": _model._sha256_file(params_path),
            "states": None, "states_sha256": None,
        }
        if snap.opt_states is not None:
            states_path = snapshot_path(prefix, snap.epoch, snap.nbatch,
                                        "states")
            states_blob = pickle.dumps(snap.opt_states)
            atomic_write_bytes(states_path, states_blob, durable=False)
            entry["states"] = os.path.basename(states_path)
            # hash the in-memory blob — no second read of the file
            entry["states_sha256"] = \
                hashlib.sha256(states_blob).hexdigest()
    entry.update({
        "opt_counts": snap.opt_counts, "rng_state": snap.rng_state,
        "metric_state": snap.metric_state, "iter_state": snap.iter_state,
    })
    if snap.iter_state is not None:
        iter_blob = json.dumps(snap.iter_state).encode()
        if len(iter_blob) > ITER_STATE_INLINE_BYTES:
            # big iterator state (shuffled ImageIter carries the whole
            # epoch permutation) becomes a per-generation sidecar; the
            # manifest keeps only the pointer + digest
            iter_path = snapshot_path(prefix, snap.epoch, snap.nbatch,
                                      "iter.json")
            atomic_write_bytes(iter_path, iter_blob, durable=False)
            entry["iter_state"] = None
            entry["iter_state_file"] = os.path.basename(iter_path)
            entry["iter_state_sha256"] = \
                hashlib.sha256(iter_blob).hexdigest()
    # the commit point: a crash before this line leaves orphan payloads
    # (swept by a later GC), never a manifest entry without its bytes
    _model._manifest_add_snapshot(prefix, entry)
    gc_snapshots(prefix, keep_last=keep_last, logger=logger)
    from . import compile_cache as _compile_cache

    if _compile_cache.recording():
        # warm-up manifest sidecar: a mid-epoch kill before the first
        # EPOCH checkpoint must still leave the resume path something to
        # pre-compile from (no-op once written and unchanged)
        _compile_cache.save_manifest_if_changed(
            _compile_cache.manifest_path(prefix))
    _telemetry.inc("resilience.checkpoint.saves")
    _telemetry.observe("resilience.checkpoint.async_write_seconds",
                       time.perf_counter() - t0)
    _telemetry.event("checkpoint.snapshot", epoch=snap.epoch,
                     nbatch=snap.nbatch, path=params_path)
    csp.end("ok", path=os.path.basename(params_path))
    return params_path


def _snapshot_save_dict(snap):
    """The on-disk key scheme of a snapshot's arrays (``arg:<name>`` /
    ``aux:<name>``) — one definition for both the single-file and the
    per-shard writers, mirrored by the split in the load paths."""
    save_dict = {("arg:%s" % k): v for k, v in snap.arg_params.items()}
    save_dict.update({("aux:%s" % k): v
                      for k, v in snap.aux_params.items()})
    return save_dict


def _write_sharded_payloads(prefix, snap, mesh_info):
    """Sharded snapshot write (``kvstore='mesh'``, world > 1): every
    array/state KEY is assigned to one of ``num_shards`` payload files
    by :func:`mxnet_tpu.elastic.assign_keys` — the same pure ownership
    math the elastic reshard uses — and each shard file is written
    atomically on its own.  The returned manifest ``entry`` carries the
    mesh shape plus each shard's filename + sha256 (the *stitching
    manifest*); committing it LAST means a kill mid-sharded-write
    leaves the previous generation fully loadable.  Resume reads every
    shard named by the manifest and stitches, so a restart onto a
    DIFFERENT mesh shape reassembles the identical state and simply
    re-derives ownership with the new world size for its own writes.
    Returns ``(shard0_path, entry)``."""
    from . import ndarray as nd
    from . import model as _model
    from .elastic import assign_keys

    num_shards = int(mesh_info["num_shards"])
    save_dict = _snapshot_save_dict(snap)
    owner = assign_keys(list(save_dict), list(range(num_shards)), 0)
    state_owner = {}
    if snap.opt_states is not None:
        state_owner = assign_keys(list(snap.opt_states),
                                  list(range(num_shards)), 0)
    shards = []
    first_path = None
    for s in range(num_shards):
        part = {k: v for k, v in save_dict.items() if owner[k] == s}
        path = snapshot_path(prefix, snap.epoch, snap.nbatch,
                             "shard%d.params" % s)
        if first_path is None:
            first_path = path
        atomic_write(path, lambda tmp, part=part: nd.save(tmp, part),
                     fault_point="checkpoint.write", durable=False)
        ent = {"params": os.path.basename(path),
               "sha256": _model._sha256_file(path),
               "states": None, "states_sha256": None}
        if snap.opt_states is not None:
            spart = {i: st for i, st in snap.opt_states.items()
                     if state_owner[i] == s}
            blob = pickle.dumps(spart)
            spath = snapshot_path(prefix, snap.epoch, snap.nbatch,
                                  "shard%d.states" % s)
            atomic_write_bytes(spath, blob, durable=False)
            ent["states"] = os.path.basename(spath)
            ent["states_sha256"] = hashlib.sha256(blob).hexdigest()
        shards.append(ent)
    entry = {
        "epoch": snap.epoch, "nbatch": snap.nbatch,
        "params": None, "sha256": None,
        "states": None, "states_sha256": None,
        "sharded": {"num_shards": num_shards,
                    "axis": mesh_info.get("axis"),
                    "mesh_axes": mesh_info.get("mesh_axes"),
                    "mesh_shape": mesh_info.get("mesh_shape"),
                    "shards": shards},
    }
    return first_path, entry


def _entry_payload_names(entry):
    """Every on-disk payload filename one manifest snapshot entry names
    (single-file generations AND per-shard files of a sharded one) —
    the unit the GC / rollback-discard passes unlink."""
    names = [entry.get(k) for k in _PAYLOAD_KEYS if entry.get(k)]
    for ent in (entry.get("sharded") or {}).get("shards", []):
        for k in ("params", "states"):
            if ent.get(k):
                names.append(ent[k])
    return names


def gc_snapshots(prefix, keep_last=None, logger=logging):
    """Prune snapshot generations beyond ``keep_last`` (newest kept).

    Order is manifest-first: the pruned generations' entries are removed
    (atomic manifest rewrite) BEFORE any payload unlink, so a crash
    mid-GC never leaves the manifest pointing at removed payloads — at
    worst an orphan payload file survives until the next GC pass, which
    also sweeps on-disk ``-snap-`` files no longer in the manifest."""
    from . import model as _model

    if keep_last is None:
        keep_last = keep_last_default()
    if keep_last < 1:
        keep_last = 1
    pruned = _model._manifest_prune_snapshots(prefix, keep_last)
    if not pruned:
        # steady state (≤ keep_last generations): nothing to do — no
        # manifest rewrite, no directory scan.  Orphans from a crash
        # mid-GC wait for the next real prune pass
        return 0
    base_dir = os.path.dirname(os.path.abspath(prefix)) or "."
    victims = []
    for entry in pruned:
        for name in _entry_payload_names(entry):
            victims.append(os.path.join(base_dir, name))
    # orphan sweep: -snap- payloads on disk but absent from the manifest
    # (a previous crash between manifest write and unlink)
    live = set()
    m = _model.checkpoint_manifest(prefix)
    for entry in (m or {}).get("snapshots", []):
        live.update(_entry_payload_names(entry))
    snap_marker = "%s-snap-" % os.path.basename(prefix)
    try:
        for name in os.listdir(base_dir):
            if name.startswith(snap_marker) and name not in live \
                    and (name.endswith(".params")
                         or name.endswith(".states")
                         or name.endswith(".iter.json")):
                victims.append(os.path.join(base_dir, name))
    except OSError:
        pass
    return _unlink_victims(victims, prefix, logger)


#: manifest keys naming on-disk payload files of one snapshot generation
_PAYLOAD_KEYS = ("params", "states", "iter_state_file")


def _unlink_victims(victims, prefix, logger):
    removed = 0
    for path in victims:
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    if removed:
        _telemetry.inc("resilience.checkpoint.pruned", removed)
        logger.debug("checkpoint GC: removed %d pruned snapshot files "
                     "under %r", removed, prefix)
    return removed


def discard_snapshots_from(prefix, epoch, logger=logging):
    """Drop every snapshot generation at or after 0-based loop epoch
    ``epoch`` — i.e. everything newer than the epoch-``epoch`` boundary
    checkpoint.  ``nan_policy='rollback'`` calls this after restoring:
    snapshots from the abandoned trajectory must not win a later
    ``resume='auto'`` recency race and resurrect the very state the
    rollback discarded.  Manifest-first like :func:`gc_snapshots`."""
    from . import model as _model

    m = _model.checkpoint_manifest(prefix)
    snaps = (m or {}).get("snapshots", [])
    doomed = [s for s in snaps if int(s.get("epoch", -1)) >= epoch]
    if not doomed:
        return 0
    keys = {(_model._snap_key(s)) for s in doomed}

    def _drop(man):
        man["snapshots"] = [s for s in man.get("snapshots", [])
                            if _model._snap_key(s) not in keys]

    _model._manifest_mutate(prefix, _drop, durable=False)
    base_dir = os.path.dirname(os.path.abspath(prefix)) or "."
    victims = [os.path.join(base_dir, name)
               for s in doomed for name in _entry_payload_names(s)]
    logger.info("rollback: discarded %d post-rollback snapshot "
                "generation(s) under %r", len(doomed), prefix)
    return _unlink_victims(victims, prefix, logger)


def _verified(path, want_sha, logger, what):
    """True when ``path`` exists and hashes to ``want_sha`` (a recorded
    digest is mandatory for snapshots — they are never trusted blind)."""
    from . import model as _model

    if not os.path.exists(path):
        logger.warning("resume: %s %s is missing; falling back", what,
                       path)
        return False
    got = _model._sha256_file(path)
    if want_sha and got != want_sha:
        logger.warning(
            "resume: %s %s failed sha256 verification (manifest %s..., "
            "file %s...); falling back to the previous generation",
            what, path, (want_sha or "")[:12], got[:12])
        return False
    return True


def _generation_candidates(prefix, manifest):
    """Every resumable generation under ``prefix`` as ``[(key, kind,
    payload)]`` in the ONE recency convention shared by the verifying
    resume scan and the supervisor's summary probe: an epoch checkpoint
    E sits at key ``(E, -1)`` (so any mid-epoch snapshot of epoch E
    sorts newer), snapshots at ``(epoch, nbatch)`` from the manifest
    (malformed entries skipped)."""
    from . import model as _model

    candidates = []
    for entry in manifest.get("snapshots", []):
        try:
            key = (int(entry["epoch"]), int(entry["nbatch"]))
        except (KeyError, TypeError, ValueError):
            continue
        candidates.append((key, "snapshot", entry))
    for epoch in _model.list_checkpoints(prefix):
        candidates.append(((epoch, -1), "epoch", epoch))
    return candidates


def latest_generation_summary(prefix):
    """Newest resumable generation under ``prefix`` from the MANIFEST
    ALONE — ``{"epoch", "nbatch", "kind"}`` (``nbatch`` None for an
    epoch checkpoint) or None.  No payload reads, no sha verification,
    no array loads: this is the cheap "where would resume='auto' land"
    probe the restart supervisor logs before each relaunch
    (tools/supervise.py ``--prefix``); the authoritative, verifying
    scan is :func:`load_latest_state` over the SAME candidate scan
    (:func:`_generation_candidates`), so the two can't disagree about
    recency."""
    from . import model as _model

    m = _model.checkpoint_manifest(prefix) or {}
    candidates = _generation_candidates(prefix, m)
    if not candidates:
        return None
    (epoch, nbatch), kind, _payload = max(candidates,
                                          key=lambda c: c[0])
    return {"epoch": epoch,
            "nbatch": None if nbatch < 0 else nbatch,
            "kind": "checkpoint" if kind == "epoch" else "snapshot"}


def load_latest_state(prefix, logger=logging, want=None):
    """The richest verified training state under ``prefix``: mid-epoch
    snapshots and epoch-boundary checkpoints in ONE recency order
    (epoch checkpoint E ≡ position ``(E, batch -1)``; snapshot ``(e,
    k)`` sorts after it when ``e > E`` or mid-epoch of ``e == E``).
    Every candidate re-verifies its payload sha256 (and, for epoch
    checkpoints, takes a full load-verify pass) before being trusted;
    corrupt generations are skipped with
    ``resilience.checkpoint.corrupt_skipped`` and the next-older one is
    tried.  With ``want=(epoch, nbatch)`` (nbatch None ≡ an epoch
    checkpoint) only that EXACT generation is considered — the elastic
    reshard's followers load precisely the generation the leader
    announced, never whatever their own manifest view surfaces — and a
    verification failure returns None instead of falling back.
    Returns :class:`TrainingState` or None."""
    from . import model as _model
    from . import ndarray as nd

    m = _model.checkpoint_manifest(prefix) or {}
    base_dir = os.path.dirname(os.path.abspath(prefix)) or "."
    candidates = _generation_candidates(prefix, m)
    if want is not None:
        wkey = (int(want[0]), -1 if want[1] is None else int(want[1]))
        candidates = [c for c in candidates if c[0] == wkey]
    candidates.sort(key=lambda c: c[0], reverse=True)
    for _key, kind, payload in candidates:
        if kind == "epoch":
            epoch = payload
            params = "%s-%04d.params" % (prefix, epoch)
            sha = (m.get("payload_sha256") or {}).get(str(epoch))
            if sha and not _verified(params, sha, logger,
                                     "epoch checkpoint"):
                _telemetry.inc("resilience.checkpoint.corrupt_skipped")
                continue
            try:
                _sym, arg, aux = _model.load_checkpoint(prefix, epoch)
            except (MXNetError, OSError, ValueError) as e:
                logger.warning(
                    "checkpoint %s failed load verification (%s); "
                    "falling back to the previous generation", params, e)
                _telemetry.inc("resilience.checkpoint.corrupt_skipped")
                continue
            states = "%s-%04d.states" % (prefix, epoch)
            return TrainingState(
                epoch=epoch, nbatch=None, arg_params=arg, aux_params=aux,
                states_path=states if os.path.exists(states) else None,
                path=params)
        entry = payload
        arg = aux = None
        states_bytes = None
        params = None
        if entry.get("sharded"):
            # stitched generation: every shard file the manifest names
            # must verify + load; any failure skips the whole generation
            loaded = _load_sharded_payloads(base_dir, entry, logger)
            if loaded is None:
                _telemetry.inc("resilience.checkpoint.corrupt_skipped")
                continue
            arg, aux, states_bytes, params = loaded
        else:
            params = os.path.join(base_dir, entry["params"])
            if not _verified(params, entry.get("sha256"), logger,
                             "snapshot payload"):
                _telemetry.inc("resilience.checkpoint.corrupt_skipped")
                continue
            if entry.get("states"):
                states = os.path.join(base_dir, entry["states"])
                if not _verified(states, entry.get("states_sha256"),
                                 logger, "snapshot optimizer states"):
                    _telemetry.inc(
                        "resilience.checkpoint.corrupt_skipped")
                    continue
                with open(states, "rb") as f:
                    states_bytes = f.read()
        iter_state = entry.get("iter_state")
        if entry.get("iter_state_file"):
            # big iterator state lives in a sidecar (see write_snapshot)
            iter_path = os.path.join(base_dir, entry["iter_state_file"])
            if not _verified(iter_path, entry.get("iter_state_sha256"),
                             logger, "snapshot iterator state"):
                _telemetry.inc("resilience.checkpoint.corrupt_skipped")
                continue
            try:
                with open(iter_path, "rb") as f:
                    iter_state = json.loads(f.read())
            except (OSError, ValueError) as e:
                logger.warning("snapshot iterator state %s failed to "
                               "parse (%s); falling back", iter_path, e)
                _telemetry.inc("resilience.checkpoint.corrupt_skipped")
                continue
        if arg is None:
            try:
                save_dict = nd.load(params)
            except (MXNetError, OSError, ValueError) as e:
                logger.warning("snapshot %s failed load verification "
                               "(%s); falling back", params, e)
                _telemetry.inc("resilience.checkpoint.corrupt_skipped")
                continue
            arg, aux = {}, {}
            for k, v in save_dict.items():
                tp, name = k.split(":", 1)
                if tp == "arg":
                    arg[name] = v
                elif tp == "aux":
                    aux[name] = v
        return TrainingState(
            epoch=int(entry["epoch"]), nbatch=int(entry["nbatch"]),
            arg_params=arg, aux_params=aux, states_bytes=states_bytes,
            rng_state=entry.get("rng_state"),
            metric_state=entry.get("metric_state"),
            iter_state=iter_state,
            opt_counts=entry.get("opt_counts"), path=params)
    return None


def _load_sharded_payloads(base_dir, entry, logger):
    """Verify + stitch one sharded snapshot generation: every shard file
    named by the manifest loads (sha256-verified first), the per-shard
    key subsets union back into the full ``arg``/``aux`` dicts and one
    merged optimizer-state tree.  The stitch is shard-count agnostic —
    it reads whatever the manifest recorded, so a resume onto a
    DIFFERENT mesh shape reassembles the identical state (the new run's
    own snapshots then re-derive key ownership for its world size via
    ``elastic.assign_keys``).  Returns ``(arg, aux, states_bytes,
    first_params_path)`` or None when any shard fails verification."""
    from . import ndarray as nd

    info = entry["sharded"]
    arg, aux = {}, {}
    states = {}
    have_states = False
    first_path = None
    for ent in info.get("shards", []):
        path = os.path.join(base_dir, ent["params"])
        if first_path is None:
            first_path = path
        if not _verified(path, ent.get("sha256"), logger,
                         "sharded snapshot payload"):
            return None
        try:
            save_dict = nd.load(path)
        except (MXNetError, OSError, ValueError) as e:
            logger.warning("sharded snapshot %s failed load verification "
                           "(%s); falling back", path, e)
            return None
        for k, v in save_dict.items():
            tp, name = k.split(":", 1)
            if tp == "arg":
                arg[name] = v
            elif tp == "aux":
                aux[name] = v
        if ent.get("states"):
            spath = os.path.join(base_dir, ent["states"])
            if not _verified(spath, ent.get("states_sha256"), logger,
                             "sharded snapshot optimizer states"):
                return None
            try:
                with open(spath, "rb") as f:
                    states.update(pickle.loads(f.read()))
                have_states = True
            except Exception as e:  # noqa: broad-except — a torn/
                # foreign pickle must fall back, never abort resume
                logger.warning("sharded snapshot states %s failed to "
                               "unpickle (%s); falling back", spath, e)
                return None
    states_bytes = pickle.dumps(states) if have_states else None
    return arg, aux, states_bytes, first_path


class AsyncSnapshotWriter:
    """ONE background thread serializing snapshots for one fit call.

    ``submit`` hands over a captured :class:`Snapshot` without blocking;
    when the writer is busy (writing, or one already queued) the new
    snapshot is DROPPED and counted — strict ≤1-in-flight back-pressure,
    because each pending snapshot pins a full set of device-side copies.
    ``close`` drains the queue (unless ``drain=False``) and JOINS the
    thread — fit's ``finally`` guarantees no leaked writer threads
    (pinned in tests/test_preemption.py)."""

    def __init__(self, prefix, keep_last=None, logger=logging,
                 sync=False):
        self.prefix = prefix
        self.keep_last = keep_last
        self.logger = logger
        #: sync=True serializes inline in submit() — the benchmark
        #: baseline (bench_extra.py ckpt_score) and a debugging aid
        self.sync = sync
        self._cv = threading.Condition()
        self._slot = None
        self._busy = False
        self._closed = False
        self._error = None
        self._thread = None
        self._warned_drop = False
        if not sync:
            self._thread = threading.Thread(
                target=self._run, name="ckpt-writer", daemon=True)
            self._thread.start()

    def submit(self, snap):
        """Queue ``snap``; False (and a counted drop) when busy."""
        if self.sync:
            self._write(snap)
            return True
        with self._cv:
            if self._closed:
                return False
            if self._busy or self._slot is not None:
                _telemetry.inc("resilience.checkpoint.async_dropped")
                # first drop warns (cadence outruns the writer — worth
                # knowing); the rest go to debug so a tight cadence does
                # not flood the log
                log = self.logger.debug if self._warned_drop \
                    else self.logger.warning
                self._warned_drop = True
                log("async checkpoint: writer busy at epoch %d batch %d; "
                    "snapshot dropped (back-pressure keeps <=1 in "
                    "flight)", snap.epoch, snap.nbatch)
                return False
            # submit timestamp rides along so the writer can histogram
            # how long the snapshot waited before serialization started
            # (resilience.checkpoint.queue_wait_seconds): the diagnostic
            # for "is the <2% async-overhead target writer-bound or
            # cadence-bound" without a bench rerun
            self._slot = (snap, time.perf_counter())
            self._cv.notify_all()
        return True

    def _write(self, snap):
        _telemetry.set_gauge("resilience.checkpoint.async_inflight", 1)
        try:
            write_snapshot(self.prefix, snap, logger=self.logger,
                           keep_last=self.keep_last)
        finally:
            _telemetry.set_gauge("resilience.checkpoint.async_inflight", 0)

    def _run(self):
        while True:
            with self._cv:
                while self._slot is None and not self._closed:
                    self._cv.wait()
                item, self._slot = self._slot, None
                if item is None:  # closed with nothing queued
                    return
                self._busy = True
            snap, t_submit = item
            _telemetry.observe("resilience.checkpoint.queue_wait_seconds",
                               time.perf_counter() - t_submit)
            try:
                self._write(snap)
            except BaseException as e:  # noqa: BLE001 — surfaced on drain
                # published under the condition lock: drain() reads and
                # clears it from the fit thread, and an unguarded
                # cross-thread hand-off can deliver a torn/stale error
                # (flagged by graftlint's lock-discipline pass)
                with self._cv:
                    self._error = e
                self.logger.warning("async checkpoint write failed: %s", e)
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def drain(self, timeout=None):
        """Block until no snapshot is queued or being written.  Re-raises
        (once) a writer-thread failure so fit surfaces it instead of
        silently training without checkpoints."""
        if not self.sync:
            with self._cv:
                self._cv.wait_for(
                    lambda: self._slot is None and not self._busy,
                    timeout=timeout)
        with self._cv:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def close(self, drain=True):
        """Stop and JOIN the writer (idempotent).  ``drain=True`` writes
        whatever is still queued first."""
        if self.sync:
            return
        if drain:
            try:
                self.drain()
            except Exception:
                if self._thread is not None:
                    with self._cv:
                        self._closed = True
                        self._cv.notify_all()
                    self._thread.join(timeout=30)
                raise
        with self._cv:
            self._closed = True
            if not drain:
                self._slot = None
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    @property
    def alive(self):
        return self._thread is not None and self._thread.is_alive()
