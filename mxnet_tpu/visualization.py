"""Network visualization — ``plot_network`` + ``print_summary``.

Reference: ``python/mxnet/visualization.py`` (316 LoC): ``plot_network``
builds a graphviz ``Digraph`` of the symbol DAG with per-op-type node styling;
``print_summary`` prints a Keras-style layer table with output shapes and
parameter counts.
"""

from __future__ import annotations

from .base import MXNetError
from .symbol import Symbol

__all__ = ["plot_network", "print_summary"]


def _param_count(shape):
    n = 1
    for s in shape or ():
        n *= s
    return n if shape else 0


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer table (reference ``visualization.py:17``).

    Parameters mirror the reference: ``shape`` is a dict of input shapes
    (e.g. ``{'data': (1, 3, 224, 224)}``).
    """
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    entry_shapes = {}
    var_shape = {}
    if shape is not None:
        var_shape, _vd, entry_aval = symbol._infer_shapes_full(dict(shape))
        entry_shapes = {k: tuple(v.shape) for k, v in entry_aval.items()}

    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields):
        line = ""
        for i, f in enumerate(fields):
            line = (line + str(f))[: positions[i] - 1]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(headers)
    print("=" * line_length)

    total_params = 0
    # auxiliary states (e.g. BatchNorm moving_mean/var) are not trainable
    # parameters and must not be counted (reference visualization.py:64-76)
    aux_names = set(symbol.list_auxiliary_states())
    nodes = symbol._nodes()
    for node in nodes:
        if node.is_variable:
            continue
        out_shape = entry_shapes.get((id(node), 0), "")
        params = 0
        prevs = []
        for child, _ci in node.inputs:
            if child.is_variable:
                if child.name in ("data",) or child.name.endswith("label") \
                        or child.name in aux_names:
                    prevs.append(child.name)
                else:
                    params += _param_count(var_shape.get(child.name))
            else:
                prevs.append(child.name)
        total_params += params
        print_row(["%s (%s)" % (node.name, node.op.name), out_shape, params,
                   ", ".join(prevs)])
        print("_" * line_length)
    print("Total params: {:,}".format(total_params))
    print("_" * line_length)
    return total_params


# per-op-type fill colors (reference ``visualization.py:176-220``)
_NODE_STYLE = {
    "FullyConnected": "#fb8072",
    "Convolution": "#fb8072",
    "Deconvolution": "#fb8072",
    "Activation": "#ffffb3",
    "LeakyReLU": "#ffffb3",
    "BatchNorm": "#bebada",
    "Pooling": "#80b1d3",
    "Concat": "#fdb462",
    "Flatten": "#fdb462",
    "Reshape": "#fdb462",
    "SoftmaxOutput": "#b3de69",
}


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz ``Digraph`` of the symbol (reference
    ``visualization.py:110``).  Returns the Digraph; caller renders it."""
    try:
        from graphviz import Digraph
    except ImportError as e:  # pragma: no cover
        raise ImportError("plot_network requires the graphviz package") from e
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")

    entry_shapes = {}
    if shape is not None:
        _vs, _vd, entry_aval = symbol._infer_shapes_full(dict(shape))
        entry_shapes = {k: tuple(v.shape) for k, v in entry_aval.items()}

    node_attrs = node_attrs or {}
    base_attrs = {"shape": "box", "fixedsize": "false", "style": "filled"}
    base_attrs.update(node_attrs)
    dot = Digraph(name=title, format=save_format)

    nodes = symbol._nodes()
    drawn = set()
    for node in nodes:
        if node.is_variable:
            looks_weight = not (node.name == "data"
                                or node.name.endswith("label")
                                or node.name.endswith("data"))
            if hide_weights and looks_weight:
                continue
            attrs = dict(base_attrs, shape="oval", fillcolor="#8dd3c7")
            dot.node(name=node.name, label=node.name, **attrs)
        else:
            label = node.op.name
            if node.op.name == "Convolution":
                label = "Convolution\n%s/%s, %s" % (
                    "x".join(str(x) for x in node.attrs.get("kernel", ())),
                    "x".join(str(x) for x in node.attrs.get("stride", (1,))),
                    node.attrs.get("num_filter", ""))
            elif node.op.name == "FullyConnected":
                label = "FullyConnected\n%s" % node.attrs.get("num_hidden", "")
            elif node.op.name == "Activation":
                label = "Activation\n%s" % node.attrs.get("act_type", "")
            elif node.op.name == "Pooling":
                label = "Pooling\n%s, %s/%s" % (
                    node.attrs.get("pool_type", ""),
                    "x".join(str(x) for x in node.attrs.get("kernel", ())),
                    "x".join(str(x) for x in node.attrs.get("stride", (1,))))
            color = _NODE_STYLE.get(node.op.name, "#fccde5")
            attrs = dict(base_attrs, fillcolor=color)
            dot.node(name=node.name, label=label, **attrs)
        drawn.add(node.name)

    for node in nodes:
        if node.is_variable or node.name not in drawn:
            continue
        for child, ci in node.inputs:
            if child.name not in drawn:
                continue
            edge_attrs = {"dir": "back", "arrowtail": "open"}
            shp = entry_shapes.get((id(child), ci))
            if shp is not None:
                edge_attrs["label"] = "x".join(str(x) for x in shp)
            dot.edge(tail_name=node.name, head_name=child.name, **edge_attrs)
    return dot
