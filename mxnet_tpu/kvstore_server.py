"""Parameter-server process for ``dist_*`` KVStore types.

Reference: ``src/kvstore/kvstore_dist_server.h`` (sync-mode per-key merge
rounds + server-side optimizer; async-mode apply-on-arrival) and
``python/mxnet/kvstore_server.py`` (auto server loop when
``DMLC_ROLE=server``).  The ps-lite ZMQ transport is replaced by
length-prefixed pickles over TCP — the host-side control/parameter plane.
On TPU pods the *gradient* plane should be in-graph ICI/DCN collectives
(``parallel/``); this PS preserves the reference's update-on-server
semantics (optimizer state lives on the server, workers only push/pull),
which collectives alone cannot express.

Wire protocol (all messages are pickled dicts, ``<u64 length><payload>``):

  register(role)                -> {rank, num_workers}
  init(key, value)              -> {version}        (first init wins)
  push(key, value, rank)        -> {version}        (version the push lands in)
  pull(key, version)            -> {value, version} (blocks until >= version)
  barrier()                     -> {}               (blocks for num_workers)
  set_optimizer(bytes)          -> {}               (pickled optimizer)
  stop()                        -> {}               (terminates the server)

Sync mode: pushes for a key accumulate per round (a worker's n-th push for
a key belongs to round n); when all ``num_workers`` land, the merged sum is
applied (updater if set, else assigned) and the key's version increments —
the per-key barrier of ``kvstore_dist_server.h:164``.  Async mode applies
every push immediately.

Elastic membership (``MXNET_ELASTIC``, docs/resilience.md "Elastic
membership & resharding"): the server doubles as the membership
coordinator.  It owns a monotonically increasing *membership epoch*;
workers join via ``register``, leave via graceful ``deregister`` or
heartbeat-death eviction, and every membership change bumps the epoch and
discards the old world's partial sync rounds.  Elastic push/pull/barrier
traffic carries the sender's epoch and is rejected with a typed
``stale_epoch`` reply when it belongs to an old world.  Extra commands:

  deregister(rank)               -> {epoch}        (graceful leave, bumps)
  membership()                   -> {epoch, ranks, num_workers}
  reshard_sync(rank)             -> {epoch, ranks, num_workers}
                                    (quiesce rendezvous: blocks until every
                                    member of the CURRENT epoch arrives;
                                    non-arrivers are evicted after the
                                    quiesce deadline)
  reshard_commit(rank, epoch)    -> {epoch}        (post-rehydration
                                    barrier; stale when membership moved)
  reshard_choice(rank, epoch[, set]) -> {epoch[, choice]}
                                    (adopted-generation rendezvous: the
                                    leader posts the snapshot generation
                                    the world rolls back to via ``set``;
                                    followers block until it lands)
  reload(key, value, epoch)      -> {version: 0}   (snapshot rehydration:
                                    set a key's value and reset its
                                    version/round bookkeeping)
"""

from __future__ import annotations

import importlib
import io as _io
import os
import pickle
import socket
import socketserver
import struct
import sys
import threading
import time
from collections import defaultdict

import numpy as np

__all__ = ["KVStoreServer", "run_server", "_init_kvstore_server_module"]

_LEN = struct.Struct("<Q")


def _pkg_mod(name):
    """Resolve a sibling package module WITHOUT the import system.

    When the auto server loop runs during ``import mxnet_tpu`` (reference
    semantics: a DMLC_ROLE=server process blocks on import), the package's
    import lock is held by the blocked main thread — handler threads doing
    ``from .optimizer import ...`` (or unpickling package classes, which
    __import__s their module) would deadlock on it.  All needed modules are
    already in sys.modules by the time the loop starts, so plain dict
    lookup is both safe and sufficient.
    """
    full = "%s.%s" % (__package__, name)
    mod = sys.modules.get(full)
    if mod is None:
        mod = importlib.import_module(full)
    return mod


def _tele():
    """The telemetry module via sys.modules (import-lock-safe inside
    handler threads, like ``_pkg_mod``); None when the package is not
    fully imported (standalone ``python kvstore_server.py``)."""
    if not __package__:
        return None
    return sys.modules.get("%s.telemetry" % __package__)


def _trace_mod():
    """The tracing module via sys.modules (same import-lock rules as
    :func:`_tele`); None when unavailable or tracing is disabled."""
    if not __package__:
        return None
    tr = sys.modules.get("%s.tracing" % __package__)
    if tr is None or not tr.enabled():
        return None
    return tr


def _elastic_knobs():
    """``(enabled, min_workers, max_workers, quiesce_deadline)`` env
    defaults.  Delegates to ``mxnet_tpu.elastic`` — the single
    definition of the knob grammar — whenever the package is loaded;
    standalone ``python kvstore_server.py`` falls back to the same
    literals (keep the two in sync)."""
    el = sys.modules.get("%s.elastic" % __package__) if __package__ \
        else None
    if el is not None:
        return (el.enabled(), el.min_workers(), el.max_workers(),
                el.quiesce_deadline())
    return (os.environ.get("MXNET_ELASTIC", "0") not in ("0", "", "false"),
            int(os.environ.get("MXNET_ELASTIC_MIN_WORKERS", "1") or 1),
            int(os.environ.get("MXNET_ELASTIC_MAX_WORKERS", "0") or 0),
            float(os.environ.get("MXNET_ELASTIC_QUIESCE_DEADLINE", "30")
                  or 30))


class _SysUnpickler(pickle.Unpickler):
    """Unpickler that prefers sys.modules over __import__ (deadlock-safe
    inside handler threads; see _pkg_mod)."""

    def find_class(self, module, name):
        mod = sys.modules.get(module)
        if mod is not None:
            return getattr(mod, name)
        return super().find_class(module, name)


def _loads(b):
    return _SysUnpickler(_io.BytesIO(b)).load()


def _freeze_states(states):
    """Shallow-clone an updater-state tree so it pickles safely OUTSIDE
    the coordinator lock: NDArray wrappers are rebuilt around their
    current jax values (immutable — an update REBINDS ``_jx``, so the
    clone keeps the view captured under the lock), containers are
    rebuilt per element."""
    ndarray = _pkg_mod("ndarray")

    def clone(v):
        if isinstance(v, ndarray.NDArray):
            return ndarray.NDArray._from_jax(v._jx, v._ctx)
        if isinstance(v, (tuple, list)):
            return type(v)(clone(x) for x in v)
        if isinstance(v, dict):
            return {k: clone(x) for k, x in v.items()}
        return v

    return clone(states)


class _Disconnected(Exception):
    """Raised inside a handler whose peer socket died mid-wait."""


class _DeadPeer(Exception):
    """A *different* worker's rank has been dead past the heartbeat
    deadline while this handler was blocked waiting on it; carries the
    human-readable diagnosis naming the lost rank."""

    def __init__(self, message):
        super().__init__(message)
        self.message = message


def _sock_dead(sock):
    """Non-blocking closed-peer probe (MSG_PEEK)."""
    try:
        return sock.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b""
    except (BlockingIOError, InterruptedError):
        return False
    except OSError:
        return True


def send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock):
    head = b""
    while len(head) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(head))
        if not chunk:
            return None
        head += chunk
    n, = _LEN.unpack(head)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return _loads(bytes(buf))


class _KeyState:
    __slots__ = ("value", "version", "rounds", "pushed", "round_base")

    def __init__(self, value):
        self.value = value
        self.version = 0
        # round -> {"sum": running fold, "folded": n, "buf": {rank: v}}:
        # contributions fold in SORTED rank order, so the merged float
        # sum is independent of push arrival order — the property that
        # makes two replays of the same schedule (elastic chaos
        # included) bit-identical.  The fold is an EAGER prefix merge
        # (see _push): only out-of-order arrivals are buffered, so the
        # server does not hold a full world's gradients per round
        self.rounds = defaultdict(dict)
        self.pushed = defaultdict(int)                # rank -> push count
        # rank -> pushed count when the rank's current incarnation
        # registered; client rounds below it predate this incarnation and
        # must not be mistaken for replays (see _push dedup)
        self.round_base = defaultdict(int)


class KVStoreServer:
    """Threaded PS: one handler thread per connection."""

    def __init__(self, num_workers, sync_mode=True, host="127.0.0.1",
                 port=0, heartbeat_deadline=None, elastic=None,
                 min_workers=None, max_workers=None, quiesce_deadline=None):
        self.num_workers = num_workers
        self.sync_mode = sync_mode
        self.keys = {}
        self.lock = threading.Condition()
        self.updater = None
        self.next_rank = 0
        self.registered = set()   # ranks ever assigned (rejoin detection)
        self.live = {}            # rank -> connection currently holding it
        self.dead_since = {}      # rank -> monotonic time its conn died
        self.last_seen = {}       # rank -> monotonic time of last message
        # dead-peer detection: a blocked sync wait (barrier, versioned
        # pull) whose missing peer has been disconnected longer than this
        # raises a clean error naming the lost rank instead of hanging
        # forever (TF-paper-style fail-fast so the job can restart from a
        # checkpoint)
        if heartbeat_deadline is None:
            heartbeat_deadline = float(os.environ.get(
                "MXNET_KVSTORE_HEARTBEAT_DEADLINE", "120"))
        self.heartbeat_deadline = heartbeat_deadline
        self.barrier_waiters = set()  # ranks arrived at the current barrier
        self.barrier_gen = 0
        self.stopped = threading.Event()
        # -- elastic membership coordinator state (all guarded by
        # self.lock; docs/resilience.md "Elastic membership") ------------
        env_elastic, env_min, env_max, env_quiesce = _elastic_knobs()
        if elastic is None:
            elastic = env_elastic
        if min_workers is None:
            min_workers = env_min
        if max_workers is None:
            max_workers = env_max
        if quiesce_deadline is None:
            quiesce_deadline = env_quiesce
        self.elastic = bool(elastic)
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.quiesce_deadline = quiesce_deadline
        self.epoch = 0            # membership epoch (monotonic)
        self.members = set()      # ranks in the current membership
        self.reshard_waiters = set()   # ranks parked at the quiesce sync
        self.reshard_gen = 0
        self.reshard_release = None    # last released membership view
        self.commit_waiters = set()    # ranks parked at the commit barrier
        self.commit_gen = 0
        self.reshard_choice = None     # leader's adopted-generation pick
        self._released_once = False    # initial cohort fully assembled

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.rank = None
                try:
                    while True:
                        msg = recv_msg(self.request)
                        if msg is None:
                            return
                        # worker↔coordinator span stitching: a verb
                        # carrying a trace context gets a server-side
                        # span parented on the sender's span (the
                        # worker's fit batch / reshard cycle), so one
                        # tree spans both processes.  No context, no
                        # span — the non-traced hot path is unchanged.
                        tr = _trace_mod()
                        wire = msg.get("trace") if tr is not None else None
                        sp = tr.start_span(
                            "kvstore.%s" % msg.get("cmd"),
                            trace_id=wire.get("trace_id"),
                            parent_id=wire.get("span_id"),
                            rank=msg.get("rank")) if wire else None
                        reply = None
                        try:
                            reply = outer.dispatch(msg, conn=self)
                        finally:
                            if sp is not None:
                                err = isinstance(reply, dict) \
                                    and "error" in reply
                                sp.end("error" if err or reply is None
                                       else "ok")
                        send_msg(self.request, reply)
                        if msg["cmd"] == "stop":
                            return
                except _Disconnected:
                    return
                finally:
                    outer.on_disconnect(self)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self.server = Server((host, port), Handler)
        self.port = self.server.server_address[1]

    def on_disconnect(self, conn):
        """A worker connection dropped: release its rank and withdraw any
        in-flight barrier contribution so the cluster cannot desync on a
        mid-barrier death + rejoin."""
        with self.lock:
            rank = getattr(conn, "rank", None)
            if rank is not None and self.live.get(rank) is conn:
                del self.live[rank]
                self.dead_since[rank] = time.monotonic()
                self.barrier_waiters.discard(rank)
                self.reshard_waiters.discard(rank)
                self.commit_waiters.discard(rank)
                self.lock.notify_all()

    # -- elastic membership (lock held throughout) -------------------------
    def _world(self):
        """Sync-round/barrier completion count: the live membership in
        elastic mode (world size changes mid-job), the launch-time
        ``num_workers`` otherwise."""
        if self.elastic and self.members:
            return len(self.members)
        return self.num_workers

    def _bump_epoch(self, reason):
        """Advance the membership epoch (lock held).  Partial sync rounds
        belong to the old world and are discarded, and every key's
        version/round bookkeeping restarts at zero — the new world's
        numbering begins clean (clients reset their push/pull counters
        when they adopt the new epoch at ``reshard_sync``), so a
        half-pushed old round can neither complete late nor shift the
        new world's rounds out of phase.  Parked waiters are woken so
        their epoch-aware predicates can return typed stale replies."""
        self.epoch += 1
        self.reshard_choice = None  # the old world's pick is void
        for st in self.keys.values():
            st.rounds.clear()
            st.pushed.clear()
            st.round_base.clear()
            st.version = 0
        t = _tele()
        if t is not None:
            t.set_gauge("elastic.epoch", self.epoch)
            t.event("elastic.membership", epoch=self.epoch, reason=reason,
                    ranks=sorted(self.members))
        self.lock.notify_all()

    def _evict(self, rank, reason):
        """Remove ``rank`` from the membership (lock held) and bump the
        epoch.  Used by graceful ``deregister``, heartbeat-death
        detection, and the reshard quiesce deadline."""
        self.members.discard(rank)
        self.dead_since.pop(rank, None)
        self.barrier_waiters.discard(rank)
        self.reshard_waiters.discard(rank)
        self.commit_waiters.discard(rank)
        t = _tele()
        if t is not None:
            t.inc("elastic.evictions", reason=reason)
        self._bump_epoch("%s rank %s" % (reason, rank))

    def _stale_reply(self, msg_epoch, cmd):
        """Typed stale-epoch reply when elastic traffic carries an old
        membership epoch (lock held); None when current.  Messages
        WITHOUT an epoch (non-elastic clients, the pre-adoption
        init/pull phase) are never checked."""
        if not self.elastic or msg_epoch is None \
                or msg_epoch == self.epoch:
            return None
        t = _tele()
        if t is not None:
            t.inc("elastic.stale_epoch.count", cmd=cmd)
        return {"error": "stale membership epoch %s (current %s) for %r: "
                         "run the reshard cycle before retrying"
                         % (msg_epoch, self.epoch, cmd),
                "stale_epoch": True, "epoch": self.epoch}

    def _member_reply(self, rank, cmd):
        """Typed reply directing a non-member of the current epoch back
        through register/reshard (lock held); None when ``rank`` is a
        member.  An evicted-but-live worker must not contribute to the
        new world's rounds."""
        if not self.elastic or rank in self.members:
            return None
        return {"error": "rank %s is not a member of membership epoch %d "
                         "(%r): re-register and reshard to rejoin"
                         % (rank, self.epoch, cmd),
                "stale_epoch": True, "epoch": self.epoch}

    def _deadline_evict(self, missing, waited, floor, reason):
        """Reshard-deadline discriminator (lock held), shared by the
        quiesce sync and the commit barrier: a live connection is
        evidence of a slow-but-alive member (a long batch, a big
        snapshot reload); a closed one is a death.  Dead missing
        members are evicted at the deadline — the epoch bump restarts
        the cycle on the survivors — while live ones get 3x before
        being treated as wedged, keeping the contract
        resume-or-typed-error, never a hang.  Returns True when the
        caller should keep waiting (members were evicted, or live
        stragglers remain), False when it should fail with its typed
        timeout error."""
        evictable = {r for r in missing if r not in self.live} \
            if waited <= 3 * self.quiesce_deadline else set(missing)
        if evictable and len(self.members) - len(evictable) >= floor:
            for r in sorted(evictable):
                self._evict(r, reason)
            return True
        return bool(missing - evictable)

    def _ok(self, reply):
        """Stamp a success reply with the current membership epoch (lock
        held): clients observe membership movement passively on the
        push/pull traffic every batch already generates, so the
        batch-boundary elastic poll costs no dedicated RPC round-trip."""
        if self.elastic:
            reply["epoch"] = self.epoch
        return reply

    # -- command dispatch --------------------------------------------------
    def dispatch(self, msg, conn=None):
        cmd = msg["cmd"]
        if cmd == "register":
            with self.lock:
                preferred = msg.get("preferred_rank")
                if self.elastic and self.max_workers:
                    joining = preferred is None \
                        or int(preferred) not in self.members
                    if joining and len(self.members) >= self.max_workers:
                        return {"error": "membership is full (%d members, "
                                         "MXNET_ELASTIC_MAX_WORKERS=%d)"
                                         % (len(self.members),
                                            self.max_workers),
                                "membership_full": True}
                if preferred is not None:
                    # restart/rejoin path (reference ps-lite is_recovery,
                    # kvstore_dist.h:35,73): a worker that announces its
                    # DMLC_WORKER_ID keeps that rank across restarts; the
                    # server's weights/versions are intact so it resumes
                    # from current state without re-running init barriers
                    rank = int(preferred)
                    if rank in self.live:
                        # recovery is only for DEAD incarnations; a live
                        # holder means a rank collision, not a restart
                        return {"error": "rank %d is held by a live "
                                         "worker" % rank}
                    recovery = rank in self.registered
                    self.registered.add(rank)
                    if not recovery:
                        self.next_rank = max(self.next_rank, rank + 1)
                else:
                    while self.next_rank in self.registered:
                        self.next_rank += 1
                    rank = self.next_rank
                    self.registered.add(rank)
                    self.next_rank += 1
                    recovery = False
                if conn is not None:
                    conn.rank = rank
                    self.live[rank] = conn
                self.dead_since.pop(rank, None)
                self.last_seen[rank] = time.monotonic()
                if not msg.get("rejoin"):
                    # a fresh worker process (not a same-process
                    # reconnect()) restarts its per-key round numbering
                    # at 0: remember the current pushed counts so its low
                    # rounds are not misread as replays
                    for st in self.keys.values():
                        st.round_base[rank] = st.pushed[rank]
                if self.elastic and rank not in self.members:
                    # a NEW member (first join, or re-admission after an
                    # eviction) changes the world: bump so every elastic
                    # worker reshards around it.  A transient reconnect of
                    # a current member (PR 1 recovery) does NOT bump.
                    self.members.add(rank)
                    self._bump_epoch("register rank %s" % rank)
                return {"rank": rank, "num_workers": self.num_workers,
                        "is_recovery": recovery, "epoch": self.epoch}
        if cmd == "deregister":
            # graceful leave: the worker announces it is going away, so
            # the membership shrinks NOW instead of after a heartbeat
            # deadline of blocked sync rounds
            with self.lock:
                if not self.elastic:
                    return {"error": "deregister requires an elastic "
                                     "server (MXNET_ELASTIC=1)"}
                rank = msg.get("rank", getattr(conn, "rank", None))
                if rank in self.members:
                    self._evict(rank, "deregister")
                return {"epoch": self.epoch}
        if cmd == "membership":
            with self.lock:
                return {"epoch": self.epoch, "ranks": sorted(self.members),
                        "num_workers": self._world()}
        if cmd == "reshard_sync":
            return self._reshard_sync(
                msg.get("rank", getattr(conn, "rank", None)), conn)
        if cmd == "reshard_commit":
            return self._reshard_commit(
                msg.get("rank", getattr(conn, "rank", None)),
                msg.get("epoch"), conn)
        if cmd == "reshard_choice":
            return self._reshard_choice(
                msg.get("rank", getattr(conn, "rank", None)),
                msg.get("epoch"), "set" in msg, msg.get("set"), conn)
        if cmd == "reload":
            with self.lock:
                stale = self._stale_reply(msg.get("epoch"), "reload")
                if stale is not None:
                    return stale
                value = np.array(msg["value"], copy=True)
                st = self.keys.get(msg["key"])
                if st is None:
                    st = self.keys[msg["key"]] = _KeyState(value)
                st.value = value
                st.version = 0
                st.rounds.clear()
                st.pushed.clear()
                st.round_base.clear()
                self.lock.notify_all()
                return {"version": 0}
        if cmd == "heartbeat":
            # liveness ping: refreshes last_seen and reports the cluster
            # view so a worker can see who the server thinks is alive
            t = _tele()
            if t is not None:
                t.inc("kvstore.server.heartbeats")
            with self.lock:
                rank = msg.get("rank", getattr(conn, "rank", None))
                if rank is not None:
                    self.last_seen[rank] = time.monotonic()
                return {"live": sorted(self.live),
                        "num_workers": self._world(),
                        "epoch": self.epoch}
        if cmd == "init":
            with self.lock:
                if msg["key"] not in self.keys:
                    self.keys[msg["key"]] = _KeyState(
                        np.array(msg["value"], copy=True))
                return {"version": self.keys[msg["key"]].version}
        if cmd == "push":
            return self._push(msg["key"], msg["value"], msg["rank"],
                              msg.get("round"), msg.get("epoch"))
        if cmd == "pull":
            return self._pull(msg["key"], msg.get("version", 0), conn,
                              msg.get("epoch"))
        if cmd == "set_optimizer":
            get_updater = _pkg_mod("optimizer").get_updater
            with self.lock:
                self.updater = get_updater(_loads(msg["bytes"]))
            return {}
        if cmd == "barrier":
            return self._barrier(msg.get("rank"),
                                 getattr(conn, "rank", None), conn,
                                 msg.get("epoch"))
        if cmd == "sync_mode":
            # reference kvstore.cc:32-35 — rank 0 commands kSyncMode to
            # servers when the type lacks _async
            with self.lock:
                self.sync_mode = bool(msg.get("value", True))
            return {}
        if cmd == "get_updater_states":
            # the elastic leader calls this once per batch (the snapshot
            # cadence), so the byte-serialization must not run under the
            # coordinator's global lock — it would stall every other
            # rank's push/pull for the duration.  State arrays are
            # immutable jax values rebound on update, so a shallow
            # wrapper clone under the lock freezes a consistent view
            # that pickles safely outside it.
            with self.lock:
                if self.updater is None:
                    return {"error": "optimizer not initialized on server"}
                frozen = _freeze_states(self.updater.states)
            return {"states": pickle.dumps(frozen)}
        if cmd == "set_updater_states":
            with self.lock:
                if self.updater is None:
                    return {"error": "optimizer not initialized on server"}
                # deadlock-safe unpickle (see _pkg_mod)
                self.updater.states = _loads(msg["states"])
            return {}
        if cmd == "user_command":
            # SendCommandToServers parity: unknown app-level commands are
            # accepted and ignored
            return {}
        if cmd == "stop":
            self.stopped.set()
            with self.lock:
                # wake parked barrier/pull/reshard waiters so their
                # handlers exit with the typed shutdown instead of
                # timing out against the heartbeat deadline
                self.lock.notify_all()
            threading.Thread(target=self.server.shutdown,
                             daemon=True).start()
            return {}
        return {"error": "unknown command %r" % cmd}

    def _apply(self, st, key, merged):
        if self.updater is not None:
            # optimizers operate on NDArrays; round-trip through one
            array = _pkg_mod("ndarray").array

            weight = array(st.value)
            self.updater(key, array(merged), weight)
            st.value = weight.asnumpy()
        else:
            st.value = np.array(merged, copy=True)

    def _push(self, key, value, rank, client_round=None, msg_epoch=None):
        value = np.asarray(value)
        t = _tele()
        if t is not None and t.enabled():
            t.inc("kvstore.server.pushes", rank=rank)
            t.inc("kvstore.server.push_bytes", int(value.nbytes))
        with self.lock:
            stale = self._stale_reply(msg_epoch, "push")
            if stale is None and msg_epoch is not None:
                # an old world's gradient must never merge into the new
                # world's rounds — and neither may an evicted-but-live
                # straggler that happens to guess the current epoch
                stale = self._member_reply(rank, "push")
            if stale is not None:
                return stale
            st = self.keys.get(key)
            if st is None:
                return {"error": "key %r not initialized" % key}
            if not self.sync_mode:
                rnd = st.pushed[rank]
                if client_round is not None \
                        and st.round_base[rank] <= client_round < rnd:
                    # replay (reply lost, worker re-pushed after
                    # reconnect()): already applied — ack, don't take a
                    # second optimizer step for the same gradient
                    return self._ok({"version": st.version})
                st.pushed[rank] += 1
                self._apply(st, key, value)
                st.version += 1
                self.lock.notify_all()
                return self._ok({"version": st.version})
            rnd = st.pushed[rank]
            if client_round is not None \
                    and st.round_base[rank] <= client_round < rnd:
                # replay of an already-counted push: the reply was lost
                # mid-transport and the worker re-pushed after
                # reconnect().  Counting it again would shift this rank's
                # contributions one round forward forever, so ack with
                # the original round's reply instead.  (Rounds below the
                # incarnation base are a restarted process's fresh
                # numbering, not replays — those take the normal path.)
                return self._ok({"version": client_round + 1})
            st.pushed[rank] += 1
            # sorted-rank fold with EAGER prefix merging: a contribution
            # folds into the running sum as soon as every lower-sorted
            # rank's has, so only out-of-order arrivals are buffered
            # (expected ~W/2 gradients, not a full world's) while the
            # float sum stays arrival-order independent.  The member set
            # is fixed for a round's lifetime — an epoch bump clears
            # st.rounds wholesale.
            order = sorted(self.members) \
                if self.elastic and self.members \
                else range(self.num_workers)
            slot = st.rounds[rnd]
            if not slot:
                slot.update(sum=None, folded=0, buf={})
            slot["buf"][rank] = value
            while slot["folded"] < len(order) \
                    and order[slot["folded"]] in slot["buf"]:
                v = slot["buf"].pop(order[slot["folded"]])
                slot["sum"] = v if slot["sum"] is None \
                    else slot["sum"] + v
                slot["folded"] += 1
            if slot["folded"] == len(order):
                assert st.version == rnd, "round applied out of order"
                self._apply(st, key, slot["sum"])
                del st.rounds[rnd]
                st.version += 1
                self.lock.notify_all()
            return self._ok({"version": rnd + 1})

    def _check_dead_peers(self, wait_started):
        """Raise _DeadPeer (lock held) when a sync wait is blocked on a
        rank whose connection has been gone past the heartbeat deadline —
        or when, after the deadline, some ranks never registered at all."""
        now = time.monotonic()
        for rank in sorted(self.dead_since):
            dead_for = now - self.dead_since[rank]
            if dead_for > self.heartbeat_deadline:
                if self.elastic and rank not in self.members:
                    # a departed non-member (graceful deregister, then
                    # the socket closed — or an already-evicted rank):
                    # the current world owes it nothing; clean up
                    # instead of poisoning parked waiters with it
                    del self.dead_since[rank]
                    continue
                if self.elastic and rank in self.members and \
                        len(self.members) - 1 >= max(1, self.min_workers):
                    # elastic eviction: a dead member LEAVES the
                    # membership instead of killing the job — the epoch
                    # bump wakes blocked waiters, whose epoch-aware
                    # predicates hand their clients typed StaleEpoch
                    # replies, and the survivors reshard around the loss
                    t = _tele()
                    if t is not None:
                        t.inc("kvstore.server.heartbeat_deaths", rank=rank)
                        t.event("kvstore.heartbeat_death", rank=rank,
                                dead_for_s=round(dead_for, 1),
                                evicted=True)
                    self._evict(rank, "heartbeat-death")
                    continue
                seen = self.last_seen.get(rank)
                seen_txt = "" if seen is None \
                    else ", last message %.1fs ago" % (now - seen)
                t = _tele()
                if t is not None:
                    t.inc("kvstore.server.heartbeat_deaths", rank=rank)
                    t.event("kvstore.heartbeat_death", rank=rank,
                            dead_for_s=round(dead_for, 1))
                raise _DeadPeer(
                    "worker rank %d lost: disconnected %.1fs ago%s "
                    "(> heartbeat deadline %.0fs)"
                    % (rank, dead_for, seen_txt, self.heartbeat_deadline))
        # `registered` is empty only before ANY worker announced itself
        # (workers register on the scheduler and announce their rank to
        # every shard server), and an empty set says nothing about worker
        # liveness — so the never-registered check must not fire then
        if self.registered \
                and len(self.registered) < self.num_workers \
                and now - wait_started > self.heartbeat_deadline:
            raise _DeadPeer(
                "only %d of %d workers ever registered within the "
                "heartbeat deadline (%.0fs); registered ranks: %s"
                % (len(self.registered), self.num_workers,
                   self.heartbeat_deadline, sorted(self.registered)))

    def _wait_interruptible(self, conn, cond, watch_peers=False):
        """Condition-wait (lock held) that notices a dead peer: a blocked
        handler thread must release its rank, or the worker's restarted
        incarnation is refused as a rank collision.  With ``watch_peers``
        the wait also fails fast — _DeadPeer naming the lost rank — when
        a rank it depends on has been dead past the heartbeat deadline."""
        started = time.monotonic()
        while not cond():
            if self.stopped.is_set():
                # server close()/stop wakes parked waiters with a typed
                # shutdown instead of leaving them to ride out the
                # heartbeat deadline against a dead server
                raise _Disconnected()
            self.lock.wait(timeout=1.0)
            if cond():
                return
            if self.stopped.is_set():
                raise _Disconnected()
            if conn is not None and _sock_dead(conn.request):
                raise _Disconnected()
            if watch_peers:
                self._check_dead_peers(started)

    def _pull(self, key, version, conn=None, msg_epoch=None):
        with self.lock:
            stale = self._stale_reply(msg_epoch, "pull")
            if stale is not None:
                return stale
            st = self.keys.get(key)
            if st is None:
                return {"error": "key %r not initialized" % key}

            def _done():
                # an epoch bump aborts the wait: the round this pull is
                # gated on belonged to the old world and was discarded
                if self.elastic and msg_epoch is not None \
                        and self.epoch != msg_epoch:
                    return True
                return st.version >= version

            try:
                self._wait_interruptible(conn, _done, watch_peers=True)
            except _DeadPeer as e:
                # a sync round can never complete without the lost rank's
                # push — fail the pull with the diagnosis, don't hang
                return {"error": "pull(%r) abandoned: %s"
                                 % (key, e.message)}
            stale = self._stale_reply(msg_epoch, "pull")
            if stale is not None:
                return stale
            return self._ok({"value": st.value, "version": st.version})

    def _barrier(self, rank, conn_rank, conn=None, msg_epoch=None):
        """Rank-tracked barrier: a dead worker's contribution is withdrawn
        by on_disconnect, so a restart cannot release a generation early
        or leave it off by one.  A barrier blocked on a rank that stays
        dead past the heartbeat deadline fails with an error naming it.
        Elastic barriers carry the sender's membership epoch and abort
        with a typed stale reply when the membership moves mid-wait."""
        with self.lock:
            stale = self._stale_reply(msg_epoch, "barrier")
            if stale is None and msg_epoch is not None:
                stale = self._member_reply(
                    rank if rank is not None else conn_rank, "barrier")
            if stale is not None:
                return stale
            gen = self.barrier_gen
            r = rank if rank is not None else conn_rank
            self.barrier_waiters.add(r)
            if len(self.barrier_waiters) == self._world():
                self.barrier_waiters.clear()
                self.barrier_gen += 1
                self.lock.notify_all()
            else:
                def _done():
                    if self.elastic and msg_epoch is not None \
                            and self.epoch != msg_epoch:
                        return True
                    return self.barrier_gen != gen

                try:
                    self._wait_interruptible(conn, _done, watch_peers=True)
                except _Disconnected:
                    self.barrier_waiters.discard(r)
                    raise
                except _DeadPeer as e:
                    self.barrier_waiters.discard(r)
                    return {"error": "barrier abandoned: %s" % e.message}
                stale = self._stale_reply(msg_epoch, "barrier")
                if stale is not None:
                    self.barrier_waiters.discard(r)
                    return stale
            return {}

    # -- elastic reshard rendezvous ----------------------------------------
    def _reshard_ready(self, floor):
        """Release condition (lock held): every member of the CURRENT
        epoch has arrived at the quiesce sync and the world is at least
        ``floor`` workers."""
        return bool(self.members) and len(self.members) >= floor \
            and self.members <= self.reshard_waiters

    def _reshard_release(self):
        """Publish the membership view all parked reshard waiters adopt
        (lock held) and advance the rendezvous generation."""
        self.reshard_release = {"epoch": self.epoch,
                                "ranks": sorted(self.members),
                                "num_workers": len(self.members)}
        self.reshard_waiters.clear()
        self.reshard_gen += 1
        self._released_once = True
        self.lock.notify_all()

    def _reshard_sync(self, rank, conn=None):
        """Quiesce rendezvous: block until every member of the current
        membership epoch arrives, then hand all of them one consistent
        ``{epoch, ranks, num_workers}`` view.  Members that fail to
        arrive within the quiesce deadline are evicted (another epoch
        bump) so a worker that died mid-reshard cannot wedge the cycle;
        when eviction would drop the world below the configured floor
        the sync fails with a typed error — resume-or-error, never a
        hang.  The initial cohort additionally waits for the full
        launch-time ``num_workers`` so a lone first worker cannot train
        solo while its peers are still registering."""
        with self.lock:
            if not self.elastic:
                return {"error": "reshard_sync requires an elastic "
                                 "server (MXNET_ELASTIC=1)"}
            not_member = self._member_reply(rank, "reshard_sync")
            if not_member is not None:
                return not_member
            floor = max(1, self.min_workers)
            if not self._released_once:
                floor = max(floor, self.num_workers)
            self.reshard_waiters.add(rank)
            gen = self.reshard_gen
            started = time.monotonic()
            seen_epoch = self.epoch
            while self.reshard_gen == gen:
                if self._reshard_ready(floor):
                    self._reshard_release()
                    break
                if self.stopped.is_set():
                    raise _Disconnected()
                self.lock.wait(timeout=0.25)
                if self.reshard_gen != gen:
                    break
                if self.epoch != seen_epoch:
                    # membership changed while parked (a join, an
                    # eviction): restart this waiter's deadline clock so
                    # a just-registered member gets a full quiesce
                    # window to arrive instead of being evicted by a
                    # clock that started before it even joined
                    seen_epoch = self.epoch
                    started = time.monotonic()
                if conn is not None and _sock_dead(conn.request):
                    self.reshard_waiters.discard(rank)
                    raise _Disconnected()
                if rank not in self.members:
                    # evicted while parked (this worker was itself past
                    # the deadline from another waiter's point of view)
                    return self._member_reply(rank, "reshard_sync")
                waited = time.monotonic() - started
                if waited > self.quiesce_deadline:
                    missing = self.members - self.reshard_waiters
                    if self._deadline_evict(missing, waited, floor,
                                            "quiesce-deadline"):
                        continue
                    self.reshard_waiters.discard(rank)
                    return {"error":
                            "elastic reshard could not assemble a world "
                            "of >= %d workers within the quiesce deadline "
                            "(%.0fs): members %s, arrived %s"
                            % (floor, self.quiesce_deadline,
                               sorted(self.members),
                               sorted(self.reshard_waiters | {rank}))}
            return dict(self.reshard_release)

    def _reshard_choice(self, rank, msg_epoch, has_set, choice, conn=None):
        """Adopted-generation rendezvous, between the quiesce sync and
        the rehydration: the membership LEADER announces which snapshot
        generation (or None) the whole world rolls back to, and every
        other member blocks here until the announcement lands.  Members
        reading the checkpoint manifest independently could adopt
        DIFFERENT generations — a straggler ex-leader's inline write
        racing the reads, shared-FS visibility lag, a per-member sha
        fallback — and silently diverge into mixed server parameters and
        disagreeing data ledgers.  Epoch-checked both ways: a membership
        change mid-rendezvous voids the stored choice (``_bump_epoch``)
        and returns typed stale replies so the whole cycle restarts."""
        with self.lock:
            if not self.elastic:
                return {"error": "reshard_choice requires an elastic "
                                 "server (MXNET_ELASTIC=1)"}
            stale = self._stale_reply(msg_epoch, "reshard_choice")
            if stale is None:
                stale = self._member_reply(rank, "reshard_choice")
            if stale is not None:
                return stale
            if has_set:
                self.reshard_choice = {"epoch": self.epoch,
                                       "choice": choice}
                self.lock.notify_all()
                return {"epoch": self.epoch}
            started = time.monotonic()
            while self.reshard_choice is None \
                    or self.reshard_choice["epoch"] != self.epoch:
                if self.stopped.is_set():
                    raise _Disconnected()
                self.lock.wait(timeout=0.25)
                stale = self._stale_reply(msg_epoch, "reshard_choice")
                if stale is not None:
                    return stale
                if conn is not None and _sock_dead(conn.request):
                    raise _Disconnected()
                if rank not in self.members:
                    return self._member_reply(rank, "reshard_choice")
                waited = time.monotonic() - started
                if waited > self.quiesce_deadline:
                    # the leader died between the sync and its
                    # announcement: its eviction bumps the epoch, every
                    # parked waiter goes stale and the cycle restarts on
                    # the shrunken world with a new leader
                    missing = {min(self.members)} if self.members \
                        else set()
                    if self._deadline_evict(missing, waited,
                                            max(1, self.min_workers),
                                            "choice-deadline"):
                        continue
                    return {"error":
                            "elastic reshard: no adopted-generation "
                            "announcement from the leader within the "
                            "quiesce deadline (%.0fs)"
                            % self.quiesce_deadline}
            return {"epoch": self.epoch,
                    "choice": self.reshard_choice["choice"]}

    def _reshard_commit(self, rank, msg_epoch, conn=None):
        """Post-rehydration barrier: every member's snapshot reloads
        (and the leader's optimizer reinstall) must be visible before
        ANY member resumes training.  Epoch-checked — a membership
        change mid-commit (a kill during the reshard itself) returns a
        typed stale reply and the whole cycle restarts."""
        with self.lock:
            stale = self._stale_reply(msg_epoch, "reshard_commit")
            if stale is None:
                stale = self._member_reply(rank, "reshard_commit")
            if stale is not None:
                return stale
            self.commit_waiters.add(rank)
            gen = self.commit_gen
            if self.members <= self.commit_waiters:
                self.commit_waiters.clear()
                self.commit_gen += 1
                self.lock.notify_all()
                return {"epoch": self.epoch}
            started = time.monotonic()
            while self.commit_gen == gen:
                if self.stopped.is_set():
                    raise _Disconnected()
                self.lock.wait(timeout=0.25)
                stale = self._stale_reply(msg_epoch, "reshard_commit")
                if stale is not None:
                    self.commit_waiters.discard(rank)
                    return stale
                if conn is not None and _sock_dead(conn.request):
                    self.commit_waiters.discard(rank)
                    raise _Disconnected()
                if self.commit_gen != gen:
                    break
                waited = time.monotonic() - started
                if waited > self.quiesce_deadline:
                    # a member died between sync and commit: its eviction
                    # turns everyone's commit stale and the cycle
                    # restarts on the new membership
                    missing = self.members - self.commit_waiters
                    if self._deadline_evict(missing, waited,
                                            max(1, self.min_workers),
                                            "commit-deadline"):
                        continue
                    self.commit_waiters.discard(rank)
                    return {"error": "elastic reshard commit timed out "
                                     "after %.0fs: members %s, committed "
                                     "%s" % (self.quiesce_deadline,
                                             sorted(self.members),
                                             sorted(self.commit_waiters
                                                    | {rank}))}
            return {"epoch": self.epoch}

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self):
        self.server.serve_forever()

    def start_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def close(self):
        """Shut down, WAKING every handler parked in a barrier/pull/
        reshard wait loop: the typed ``_Disconnected`` shutdown closes
        their connections promptly (clients see ``ConnectionLost``)
        instead of leaving them to ride out the heartbeat deadline."""
        self.stopped.set()
        with self.lock:
            self.lock.notify_all()
        self.server.shutdown()
        self.server.server_close()


def run_server():
    """Blocking server main (the reference ``KVStoreServer.run`` loop)."""
    # a parameter server is a host-side component (reference servers are
    # CPU processes): pin jax to CPU before any backend initializes, or
    # the server's optimizer applies (NDArray math) grab the accelerator
    # out from under the workers — on the tunneled single-chip backend
    # that deadlocks the first server-side update
    import jax

    jax.config.update("jax_platforms", "cpu")
    num_workers = int(os.environ["DMLC_NUM_WORKER"])
    # multi-server sharding (reference ps-lite N servers + EncodeKey,
    # kvstore_dist.h:40): server i listens at root port + i; workers
    # route keys/big-array chunks by server id, server 0 doubles as the
    # scheduler (rank assignment, barrier)
    sid = int(os.environ.get("DMLC_SERVER_ID", "0"))
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9090")) + sid
    # bind address is separate from the advertised DMLC_PS_ROOT_URI: on
    # multi-host launches the hostname may resolve to loopback locally
    # (Debian's 127.0.1.1 convention), so bind all interfaces whenever the
    # advertised address is non-loopback
    advertised = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    default_bind = advertised if advertised in ("127.0.0.1", "localhost") \
        else ""
    host = os.environ.get("MXNET_PS_BIND_HOST", default_bind)
    # mode is commanded by the workers (kSyncMode); start async
    srv = KVStoreServer(num_workers, sync_mode=False, host=host, port=port)
    srv.serve_forever()


def _init_kvstore_server_module():
    """Reference ``python/mxnet/kvstore_server.py`` auto-loop: a process
    started with DMLC_ROLE=server becomes a server and never returns."""
    if os.environ.get("DMLC_ROLE") == "server":
        run_server()
        os._exit(0)


if __name__ == "__main__":
    run_server()
