"""Parameter-server process for ``dist_*`` KVStore types.

Reference: ``src/kvstore/kvstore_dist_server.h`` (sync-mode per-key merge
rounds + server-side optimizer; async-mode apply-on-arrival) and
``python/mxnet/kvstore_server.py`` (auto server loop when
``DMLC_ROLE=server``).  The ps-lite ZMQ transport is replaced by
length-prefixed pickles over TCP — the host-side control/parameter plane.
On TPU pods the *gradient* plane should be in-graph ICI/DCN collectives
(``parallel/``); this PS preserves the reference's update-on-server
semantics (optimizer state lives on the server, workers only push/pull),
which collectives alone cannot express.

Wire protocol (all messages are pickled dicts, ``<u64 length><payload>``):

  register(role)                -> {rank, num_workers}
  init(key, value)              -> {version}        (first init wins)
  push(key, value, rank)        -> {version}        (version the push lands in)
  pull(key, version)            -> {value, version} (blocks until >= version)
  barrier()                     -> {}               (blocks for num_workers)
  set_optimizer(bytes)          -> {}               (pickled optimizer)
  stop()                        -> {}               (terminates the server)

Sync mode: pushes for a key accumulate per round (a worker's n-th push for
a key belongs to round n); when all ``num_workers`` land, the merged sum is
applied (updater if set, else assigned) and the key's version increments —
the per-key barrier of ``kvstore_dist_server.h:164``.  Async mode applies
every push immediately.
"""

from __future__ import annotations

import importlib
import io as _io
import os
import pickle
import socket
import socketserver
import struct
import sys
import threading
import time
from collections import defaultdict

import numpy as np

__all__ = ["KVStoreServer", "run_server", "_init_kvstore_server_module"]

_LEN = struct.Struct("<Q")


def _pkg_mod(name):
    """Resolve a sibling package module WITHOUT the import system.

    When the auto server loop runs during ``import mxnet_tpu`` (reference
    semantics: a DMLC_ROLE=server process blocks on import), the package's
    import lock is held by the blocked main thread — handler threads doing
    ``from .optimizer import ...`` (or unpickling package classes, which
    __import__s their module) would deadlock on it.  All needed modules are
    already in sys.modules by the time the loop starts, so plain dict
    lookup is both safe and sufficient.
    """
    full = "%s.%s" % (__package__, name)
    mod = sys.modules.get(full)
    if mod is None:
        mod = importlib.import_module(full)
    return mod


def _tele():
    """The telemetry module via sys.modules (import-lock-safe inside
    handler threads, like ``_pkg_mod``); None when the package is not
    fully imported (standalone ``python kvstore_server.py``)."""
    if not __package__:
        return None
    return sys.modules.get("%s.telemetry" % __package__)


class _SysUnpickler(pickle.Unpickler):
    """Unpickler that prefers sys.modules over __import__ (deadlock-safe
    inside handler threads; see _pkg_mod)."""

    def find_class(self, module, name):
        mod = sys.modules.get(module)
        if mod is not None:
            return getattr(mod, name)
        return super().find_class(module, name)


def _loads(b):
    return _SysUnpickler(_io.BytesIO(b)).load()


class _Disconnected(Exception):
    """Raised inside a handler whose peer socket died mid-wait."""


class _DeadPeer(Exception):
    """A *different* worker's rank has been dead past the heartbeat
    deadline while this handler was blocked waiting on it; carries the
    human-readable diagnosis naming the lost rank."""

    def __init__(self, message):
        super().__init__(message)
        self.message = message


def _sock_dead(sock):
    """Non-blocking closed-peer probe (MSG_PEEK)."""
    try:
        return sock.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b""
    except (BlockingIOError, InterruptedError):
        return False
    except OSError:
        return True


def send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock):
    head = b""
    while len(head) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(head))
        if not chunk:
            return None
        head += chunk
    n, = _LEN.unpack(head)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return _loads(bytes(buf))


class _KeyState:
    __slots__ = ("value", "version", "rounds", "pushed", "round_base")

    def __init__(self, value):
        self.value = value
        self.version = 0
        self.rounds = defaultdict(lambda: [None, 0])  # round -> [sum, count]
        self.pushed = defaultdict(int)                # rank -> push count
        # rank -> pushed count when the rank's current incarnation
        # registered; client rounds below it predate this incarnation and
        # must not be mistaken for replays (see _push dedup)
        self.round_base = defaultdict(int)


class KVStoreServer:
    """Threaded PS: one handler thread per connection."""

    def __init__(self, num_workers, sync_mode=True, host="127.0.0.1",
                 port=0, heartbeat_deadline=None):
        self.num_workers = num_workers
        self.sync_mode = sync_mode
        self.keys = {}
        self.lock = threading.Condition()
        self.updater = None
        self.next_rank = 0
        self.registered = set()   # ranks ever assigned (rejoin detection)
        self.live = {}            # rank -> connection currently holding it
        self.dead_since = {}      # rank -> monotonic time its conn died
        self.last_seen = {}       # rank -> monotonic time of last message
        # dead-peer detection: a blocked sync wait (barrier, versioned
        # pull) whose missing peer has been disconnected longer than this
        # raises a clean error naming the lost rank instead of hanging
        # forever (TF-paper-style fail-fast so the job can restart from a
        # checkpoint)
        if heartbeat_deadline is None:
            heartbeat_deadline = float(os.environ.get(
                "MXNET_KVSTORE_HEARTBEAT_DEADLINE", "120"))
        self.heartbeat_deadline = heartbeat_deadline
        self.barrier_waiters = set()  # ranks arrived at the current barrier
        self.barrier_gen = 0
        self.stopped = threading.Event()

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.rank = None
                try:
                    while True:
                        msg = recv_msg(self.request)
                        if msg is None:
                            return
                        reply = outer.dispatch(msg, conn=self)
                        send_msg(self.request, reply)
                        if msg["cmd"] == "stop":
                            return
                except _Disconnected:
                    return
                finally:
                    outer.on_disconnect(self)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self.server = Server((host, port), Handler)
        self.port = self.server.server_address[1]

    def on_disconnect(self, conn):
        """A worker connection dropped: release its rank and withdraw any
        in-flight barrier contribution so the cluster cannot desync on a
        mid-barrier death + rejoin."""
        with self.lock:
            rank = getattr(conn, "rank", None)
            if rank is not None and self.live.get(rank) is conn:
                del self.live[rank]
                self.dead_since[rank] = time.monotonic()
                self.barrier_waiters.discard(rank)
                self.lock.notify_all()

    # -- command dispatch --------------------------------------------------
    def dispatch(self, msg, conn=None):
        cmd = msg["cmd"]
        if cmd == "register":
            with self.lock:
                preferred = msg.get("preferred_rank")
                if preferred is not None:
                    # restart/rejoin path (reference ps-lite is_recovery,
                    # kvstore_dist.h:35,73): a worker that announces its
                    # DMLC_WORKER_ID keeps that rank across restarts; the
                    # server's weights/versions are intact so it resumes
                    # from current state without re-running init barriers
                    rank = int(preferred)
                    if rank in self.live:
                        # recovery is only for DEAD incarnations; a live
                        # holder means a rank collision, not a restart
                        return {"error": "rank %d is held by a live "
                                         "worker" % rank}
                    recovery = rank in self.registered
                    self.registered.add(rank)
                    if not recovery:
                        self.next_rank = max(self.next_rank, rank + 1)
                else:
                    while self.next_rank in self.registered:
                        self.next_rank += 1
                    rank = self.next_rank
                    self.registered.add(rank)
                    self.next_rank += 1
                    recovery = False
                if conn is not None:
                    conn.rank = rank
                    self.live[rank] = conn
                self.dead_since.pop(rank, None)
                self.last_seen[rank] = time.monotonic()
                if not msg.get("rejoin"):
                    # a fresh worker process (not a same-process
                    # reconnect()) restarts its per-key round numbering
                    # at 0: remember the current pushed counts so its low
                    # rounds are not misread as replays
                    for st in self.keys.values():
                        st.round_base[rank] = st.pushed[rank]
            return {"rank": rank, "num_workers": self.num_workers,
                    "is_recovery": recovery}
        if cmd == "heartbeat":
            # liveness ping: refreshes last_seen and reports the cluster
            # view so a worker can see who the server thinks is alive
            t = _tele()
            if t is not None:
                t.inc("kvstore.server.heartbeats")
            with self.lock:
                rank = msg.get("rank", getattr(conn, "rank", None))
                if rank is not None:
                    self.last_seen[rank] = time.monotonic()
                return {"live": sorted(self.live),
                        "num_workers": self.num_workers}
        if cmd == "init":
            with self.lock:
                if msg["key"] not in self.keys:
                    self.keys[msg["key"]] = _KeyState(
                        np.array(msg["value"], copy=True))
                return {"version": self.keys[msg["key"]].version}
        if cmd == "push":
            return self._push(msg["key"], msg["value"], msg["rank"],
                              msg.get("round"))
        if cmd == "pull":
            return self._pull(msg["key"], msg.get("version", 0), conn)
        if cmd == "set_optimizer":
            get_updater = _pkg_mod("optimizer").get_updater
            with self.lock:
                self.updater = get_updater(_loads(msg["bytes"]))
            return {}
        if cmd == "barrier":
            return self._barrier(msg.get("rank"),
                                 getattr(conn, "rank", None), conn)
        if cmd == "sync_mode":
            # reference kvstore.cc:32-35 — rank 0 commands kSyncMode to
            # servers when the type lacks _async
            with self.lock:
                self.sync_mode = bool(msg.get("value", True))
            return {}
        if cmd == "get_updater_states":
            with self.lock:
                if self.updater is None:
                    return {"error": "optimizer not initialized on server"}
                return {"states": pickle.dumps(self.updater.states)}
        if cmd == "set_updater_states":
            with self.lock:
                if self.updater is None:
                    return {"error": "optimizer not initialized on server"}
                # deadlock-safe unpickle (see _pkg_mod)
                self.updater.states = _loads(msg["states"])
            return {}
        if cmd == "user_command":
            # SendCommandToServers parity: unknown app-level commands are
            # accepted and ignored
            return {}
        if cmd == "stop":
            self.stopped.set()
            threading.Thread(target=self.server.shutdown,
                             daemon=True).start()
            return {}
        return {"error": "unknown command %r" % cmd}

    def _apply(self, st, key, merged):
        if self.updater is not None:
            # optimizers operate on NDArrays; round-trip through one
            array = _pkg_mod("ndarray").array

            weight = array(st.value)
            self.updater(key, array(merged), weight)
            st.value = weight.asnumpy()
        else:
            st.value = np.array(merged, copy=True)

    def _push(self, key, value, rank, client_round=None):
        value = np.asarray(value)
        t = _tele()
        if t is not None and t.enabled():
            t.inc("kvstore.server.pushes", rank=rank)
            t.inc("kvstore.server.push_bytes", int(value.nbytes))
        with self.lock:
            st = self.keys.get(key)
            if st is None:
                return {"error": "key %r not initialized" % key}
            if not self.sync_mode:
                rnd = st.pushed[rank]
                if client_round is not None \
                        and st.round_base[rank] <= client_round < rnd:
                    # replay (reply lost, worker re-pushed after
                    # reconnect()): already applied — ack, don't take a
                    # second optimizer step for the same gradient
                    return {"version": st.version}
                st.pushed[rank] += 1
                self._apply(st, key, value)
                st.version += 1
                self.lock.notify_all()
                return {"version": st.version}
            rnd = st.pushed[rank]
            if client_round is not None \
                    and st.round_base[rank] <= client_round < rnd:
                # replay of an already-counted push: the reply was lost
                # mid-transport and the worker re-pushed after
                # reconnect().  Counting it again would shift this rank's
                # contributions one round forward forever, so ack with
                # the original round's reply instead.  (Rounds below the
                # incarnation base are a restarted process's fresh
                # numbering, not replays — those take the normal path.)
                return {"version": client_round + 1}
            st.pushed[rank] += 1
            slot = st.rounds[rnd]
            slot[0] = value if slot[0] is None else slot[0] + value
            slot[1] += 1
            if slot[1] == self.num_workers:
                assert st.version == rnd, "round applied out of order"
                self._apply(st, key, slot[0])
                del st.rounds[rnd]
                st.version += 1
                self.lock.notify_all()
            return {"version": rnd + 1}

    def _check_dead_peers(self, wait_started):
        """Raise _DeadPeer (lock held) when a sync wait is blocked on a
        rank whose connection has been gone past the heartbeat deadline —
        or when, after the deadline, some ranks never registered at all."""
        now = time.monotonic()
        for rank in sorted(self.dead_since):
            dead_for = now - self.dead_since[rank]
            if dead_for > self.heartbeat_deadline:
                seen = self.last_seen.get(rank)
                seen_txt = "" if seen is None \
                    else ", last message %.1fs ago" % (now - seen)
                t = _tele()
                if t is not None:
                    t.inc("kvstore.server.heartbeat_deaths", rank=rank)
                    t.event("kvstore.heartbeat_death", rank=rank,
                            dead_for_s=round(dead_for, 1))
                raise _DeadPeer(
                    "worker rank %d lost: disconnected %.1fs ago%s "
                    "(> heartbeat deadline %.0fs)"
                    % (rank, dead_for, seen_txt, self.heartbeat_deadline))
        # `registered` is empty only before ANY worker announced itself
        # (workers register on the scheduler and announce their rank to
        # every shard server), and an empty set says nothing about worker
        # liveness — so the never-registered check must not fire then
        if self.registered \
                and len(self.registered) < self.num_workers \
                and now - wait_started > self.heartbeat_deadline:
            raise _DeadPeer(
                "only %d of %d workers ever registered within the "
                "heartbeat deadline (%.0fs); registered ranks: %s"
                % (len(self.registered), self.num_workers,
                   self.heartbeat_deadline, sorted(self.registered)))

    def _wait_interruptible(self, conn, cond, watch_peers=False):
        """Condition-wait (lock held) that notices a dead peer: a blocked
        handler thread must release its rank, or the worker's restarted
        incarnation is refused as a rank collision.  With ``watch_peers``
        the wait also fails fast — _DeadPeer naming the lost rank — when
        a rank it depends on has been dead past the heartbeat deadline."""
        started = time.monotonic()
        while not cond():
            self.lock.wait(timeout=1.0)
            if cond():
                return
            if conn is not None and _sock_dead(conn.request):
                raise _Disconnected()
            if watch_peers:
                self._check_dead_peers(started)

    def _pull(self, key, version, conn=None):
        with self.lock:
            st = self.keys.get(key)
            if st is None:
                return {"error": "key %r not initialized" % key}
            try:
                self._wait_interruptible(
                    conn, lambda: st.version >= version, watch_peers=True)
            except _DeadPeer as e:
                # a sync round can never complete without the lost rank's
                # push — fail the pull with the diagnosis, don't hang
                return {"error": "pull(%r) abandoned: %s"
                                 % (key, e.message)}
            return {"value": st.value, "version": st.version}

    def _barrier(self, rank, conn_rank, conn=None):
        """Rank-tracked barrier: a dead worker's contribution is withdrawn
        by on_disconnect, so a restart cannot release a generation early
        or leave it off by one.  A barrier blocked on a rank that stays
        dead past the heartbeat deadline fails with an error naming it."""
        with self.lock:
            gen = self.barrier_gen
            r = rank if rank is not None else conn_rank
            self.barrier_waiters.add(r)
            if len(self.barrier_waiters) == self.num_workers:
                self.barrier_waiters.clear()
                self.barrier_gen += 1
                self.lock.notify_all()
            else:
                try:
                    self._wait_interruptible(
                        conn, lambda: self.barrier_gen != gen,
                        watch_peers=True)
                except _Disconnected:
                    self.barrier_waiters.discard(r)
                    raise
                except _DeadPeer as e:
                    self.barrier_waiters.discard(r)
                    return {"error": "barrier abandoned: %s" % e.message}
            return {}

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self):
        self.server.serve_forever()

    def start_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def run_server():
    """Blocking server main (the reference ``KVStoreServer.run`` loop)."""
    # a parameter server is a host-side component (reference servers are
    # CPU processes): pin jax to CPU before any backend initializes, or
    # the server's optimizer applies (NDArray math) grab the accelerator
    # out from under the workers — on the tunneled single-chip backend
    # that deadlocks the first server-side update
    import jax

    jax.config.update("jax_platforms", "cpu")
    num_workers = int(os.environ["DMLC_NUM_WORKER"])
    # multi-server sharding (reference ps-lite N servers + EncodeKey,
    # kvstore_dist.h:40): server i listens at root port + i; workers
    # route keys/big-array chunks by server id, server 0 doubles as the
    # scheduler (rank assignment, barrier)
    sid = int(os.environ.get("DMLC_SERVER_ID", "0"))
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9090")) + sid
    # bind address is separate from the advertised DMLC_PS_ROOT_URI: on
    # multi-host launches the hostname may resolve to loopback locally
    # (Debian's 127.0.1.1 convention), so bind all interfaces whenever the
    # advertised address is non-loopback
    advertised = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    default_bind = advertised if advertised in ("127.0.0.1", "localhost") \
        else ""
    host = os.environ.get("MXNET_PS_BIND_HOST", default_bind)
    # mode is commanded by the workers (kSyncMode); start async
    srv = KVStoreServer(num_workers, sync_mode=False, host=host, port=port)
    srv.serve_forever()


def _init_kvstore_server_module():
    """Reference ``python/mxnet/kvstore_server.py`` auto-loop: a process
    started with DMLC_ROLE=server becomes a server and never returns."""
    if os.environ.get("DMLC_ROLE") == "server":
        run_server()
        os._exit(0)


if __name__ == "__main__":
    run_server()
