"""Imperative NDArray API (``mx.nd``).

Reference: ``include/mxnet/ndarray.h`` + ``src/ndarray/ndarray.cc`` +
``python/mxnet/ndarray.py`` (SURVEY §2.1/§2.6).

TPU-native design: an NDArray owns a ``jax.Array`` (a PJRT buffer on the
context's device).  The reference's async engine semantics map 1:1 onto
JAX/PJRT async dispatch — every op returns immediately with a future-backed
buffer, and ``asnumpy()``/``wait_to_read()`` are the sync points (reference
``NDArray::WaitToRead`` ``ndarray.h:126``; here ``block_until_ready``).
Dependency ordering needs no engine: data dependencies ARE the XLA/PJRT
dataflow.  Mutation (``a[:] = x``, ``+=``) rebinds the underlying buffer,
which matches the reference's write-var semantics for every reader that goes
through the NDArray object.

The ``mx.nd.<op>`` functions are generated from the op registry at import —
the analog of ``_init_ndarray_module`` (``python/mxnet/_ctypes/ndarray.py:155``)
generating functions from the C op registry.  Each call dispatches through a
jit-cached XLA computation (``ops/registry.py:jitted_apply``).
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

from . import profiler as _profiler
from . import random as _random
from .base import MXNetError
from .context import Context, current_context
from .ops import registry as _reg
from .ops.matrix import _infer_reshape

__all__ = ["NDArray", "array", "zeros", "ones", "empty", "full", "arange",
           "concatenate", "load", "save", "imdecode", "onehot_encode", "waitall"]

# generated op functions shadow some builtins at module level (nd.slice,
# nd.sum, ...) — keep safe references for use inside this module
_py_slice = slice


def _np_dtype(dtype):
    if dtype is None:
        return np.float32
    if str(dtype) == "bfloat16":
        return jnp.bfloat16
    return np.dtype(dtype)


class NDArray:
    """A tensor on a device context, with async-dispatch semantics."""

    __slots__ = ["_jx", "_ctx"]
    # numpy should defer to our reflected ops
    __array_priority__ = 100.0

    def __init__(self, data, ctx=None, dtype=None):
        if isinstance(data, NDArray):
            self._jx = data._jx
            self._ctx = ctx or data._ctx
            return
        ctx = ctx or current_context()
        arr = np.asarray(data, dtype=_np_dtype(dtype) if dtype else None)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        self._jx = jax.device_put(arr, ctx.jax_device())
        self._ctx = ctx

    def _transfer_src(self):
        """What the executor should hand to ``jax.device_put`` when this
        array feeds a bound input — overridden by host-backed arrays to
        expose the raw numpy buffer (one host→device copy, no staging)."""
        return self._jx

    @staticmethod
    def _from_jax(jx, ctx=None):
        out = NDArray.__new__(NDArray)
        out._jx = jx
        if ctx is None:
            plat = jx.devices().pop().platform if hasattr(jx, "devices") else "cpu"
            ctx = Context("cpu" if plat == "cpu" else "tpu", 0)
        out._ctx = ctx
        return out

    # -- properties -------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._jx.shape)

    @property
    def dtype(self):
        dt = self._jx.dtype
        return dt.type if hasattr(dt, "type") and dt.names is None else dt

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._jx.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def T(self):
        return NDArray._from_jax(self._jx.T, self._ctx)

    # -- sync points ------------------------------------------------------
    def asnumpy(self):
        """Blocking copy to host (reference ``ndarray.py`` asnumpy; the sync
        point, like WaitToRead + CopyDeviceToCPU)."""
        return np.asarray(self._jx)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def wait_to_read(self):
        self._jx.block_until_ready()

    wait_to_write = wait_to_read

    # -- conversions / movement ------------------------------------------
    def astype(self, dtype):
        return NDArray._from_jax(self._jx.astype(_np_dtype(dtype)), self._ctx)

    def copy(self):
        return NDArray._from_jax(self._jx + 0, self._ctx)

    def copyto(self, other):
        """reference ``ndarray.py`` copyto(Context|NDArray)"""
        if isinstance(other, Context):
            return NDArray._from_jax(
                jax.device_put(self._jx, other.jax_device()), other)
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise MXNetError("copyto: shape mismatch %s vs %s"
                                 % (self.shape, other.shape))
            # preserve the destination's (possibly mesh-) sharding so copies
            # into globally-placed arrays stay global
            other._jx = jax.device_put(self._jx.astype(other._jx.dtype),
                                       other._jx.sharding)
            return other
        raise TypeError("copyto does not support type " + str(type(other)))

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    def detach(self):
        return NDArray._from_jax(jax.lax.stop_gradient(self._jx), self._ctx)

    # -- shape ops --------------------------------------------------------
    def reshape(self, shape, **kwargs):
        if isinstance(shape, int):
            shape = (shape,)
        return NDArray._from_jax(
            self._jx.reshape(_infer_reshape(tuple(shape), self.shape)), self._ctx)

    def broadcast_to(self, shape):
        return NDArray._from_jax(jnp.broadcast_to(self._jx, shape), self._ctx)

    def expand_dims(self, axis):
        return NDArray._from_jax(jnp.expand_dims(self._jx, axis), self._ctx)

    def flatten(self):
        return NDArray._from_jax(self._jx.reshape(self.shape[0], -1), self._ctx)

    def transpose(self, axes=None):
        return NDArray._from_jax(jnp.transpose(self._jx, axes), self._ctx)

    def slice_axis(self, axis, begin, end):
        idx = [_py_slice(None)] * self.ndim
        idx[axis] = _py_slice(begin, end)
        return NDArray._from_jax(self._jx[tuple(idx)], self._ctx)

    # -- indexing ---------------------------------------------------------
    def _idx(self, key):
        if isinstance(key, NDArray):
            return key._jx
        if isinstance(key, tuple):
            return tuple(k._jx if isinstance(k, NDArray) else k for k in key)
        return key

    def __getitem__(self, key):
        return NDArray._from_jax(self._jx[self._idx(key)], self._ctx)

    def __setitem__(self, key, value):
        v = value._jx if isinstance(value, NDArray) else value
        if isinstance(key, _py_slice) and key == _py_slice(None):
            if np.isscalar(v):
                self._jx = jnp.full_like(self._jx, v)
            else:
                self._jx = jnp.broadcast_to(
                    jnp.asarray(v, self._jx.dtype), self.shape)
                self._jx = jax.device_put(self._jx, self._ctx.jax_device())
        else:
            self._jx = self._jx.at[self._idx(key)].set(v)

    def __len__(self):
        return self.shape[0]

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # -- arithmetic -------------------------------------------------------
    def _binop(self, other, fn):
        o = other._jx if isinstance(other, NDArray) else other
        return NDArray._from_jax(fn(self._jx, o), self._ctx)

    def __add__(self, o):
        return self._binop(o, jnp.add)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, jnp.subtract)

    def __rsub__(self, o):
        return self._binop(o, lambda a, b: jnp.subtract(b, a))

    def __mul__(self, o):
        return self._binop(o, jnp.multiply)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, jnp.divide)

    __div__ = __truediv__

    def __rtruediv__(self, o):
        return self._binop(o, lambda a, b: jnp.divide(b, a))

    __rdiv__ = __rtruediv__

    def __pow__(self, o):
        return self._binop(o, jnp.power)

    def __mod__(self, o):
        return self._binop(o, jnp.mod)

    def __neg__(self):
        return NDArray._from_jax(-self._jx, self._ctx)

    def __abs__(self):
        return NDArray._from_jax(jnp.abs(self._jx), self._ctx)

    def __iadd__(self, o):
        self._jx = self._binop(o, jnp.add)._jx
        return self

    def __isub__(self, o):
        self._jx = self._binop(o, jnp.subtract)._jx
        return self

    def __imul__(self, o):
        self._jx = self._binop(o, jnp.multiply)._jx
        return self

    def __itruediv__(self, o):
        self._jx = self._binop(o, jnp.divide)._jx
        return self

    def _cmp(self, o, fn):
        return self._binop(o, lambda a, b: fn(a, b).astype(a.dtype))

    def __eq__(self, o):
        if o is None:
            return False
        return self._cmp(o, jnp.equal)

    def __ne__(self, o):
        if o is None:
            return True
        return self._cmp(o, jnp.not_equal)

    def __gt__(self, o):
        return self._cmp(o, jnp.greater)

    def __ge__(self, o):
        return self._cmp(o, jnp.greater_equal)

    def __lt__(self, o):
        return self._cmp(o, jnp.less)

    def __le__(self, o):
        return self._cmp(o, jnp.less_equal)

    __hash__ = object.__hash__

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __repr__(self):
        return "<NDArray %s @%s>\n%s" % (
            "x".join(str(s) for s in self.shape), self._ctx, self.asnumpy())

    # -- persistence hooks ------------------------------------------------
    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx_type": self._ctx.device_typeid,
                "ctx_id": self._ctx.device_id}

    def __setstate__(self, st):
        ctx = Context(st["ctx_type"], st["ctx_id"])
        try:
            dev = ctx.jax_device()
        except Exception:
            ctx = Context("cpu", 0)
            dev = ctx.jax_device()
        self._jx = jax.device_put(st["data"], dev)
        self._ctx = ctx


# ---------------------------------------------------------------------------
# creation functions (reference python/mxnet/ndarray.py factory fns)
# ---------------------------------------------------------------------------
def array(source_array, ctx=None, dtype=None):
    return NDArray(source_array, ctx=ctx, dtype=dtype)


class _HostNDArray(NDArray):
    """Iterator fast-path NDArray: numpy-backed until first real use.

    ``_jx`` materializes (``device_put`` onto ``_ctx``) the moment any
    NDArray semantics are exercised — arithmetic, slicing, ``copyto``,
    ``wait_to_read`` — so the full NDArray contract holds.  The one
    consumer that must NOT trigger materialization is the executor's
    input transfer (``_transfer_src``), which moves the raw buffer
    host→device in a single copy.  ``asnumpy`` on the un-materialized
    buffer returns a COPY, preserving the "asnumpy is never aliased"
    contract while the executor may still read the original buffer.
    """

    __slots__ = []

    @property
    def _jx(self):
        v = NDArray._jx.__get__(self)
        if isinstance(v, np.ndarray):
            v = jax.device_put(v, self._ctx.jax_device())
            NDArray._jx.__set__(self, v)
        return v

    @_jx.setter
    def _jx(self, v):
        NDArray._jx.__set__(self, v)

    def _transfer_src(self):
        return NDArray._jx.__get__(self)  # raw buffer; no materialization

    # shape/dtype inspection must not force materialization (Module
    # checks provide_data shapes on every batch)
    @property
    def shape(self):
        return tuple(NDArray._jx.__get__(self).shape)

    @property
    def dtype(self):
        dt = NDArray._jx.__get__(self).dtype
        return dt.type if hasattr(dt, "type") and dt.names is None else dt

    @property
    def ndim(self):
        return NDArray._jx.__get__(self).ndim

    def asnumpy(self):
        v = NDArray._jx.__get__(self)
        if isinstance(v, np.ndarray):
            return v.copy()
        return np.asarray(v)

    def wait_to_read(self):
        v = NDArray._jx.__get__(self)
        if not isinstance(v, np.ndarray):
            v.block_until_ready()

    wait_to_write = wait_to_read


def from_host(source_array, ctx=None):
    """Wrap a freshly-allocated host numpy array WITHOUT the staging copy.

    The returned NDArray carries the numpy buffer as-is until first use;
    the executor's input ``device_put`` moves it host→device directly
    (one copy total, instead of numpy→CPU-jax→device).  This is the
    data-iterator fast path — a 128×3×224×224 f32 batch is 77 MB, and
    ``jax.device_put`` to the CPU backend costs ~0.3 ms/img of pure
    memcpy the training device never needed.

    Contract: the caller must NOT mutate ``source_array`` after wrapping
    (iterators allocate a fresh batch buffer per ``next()``).
    """
    arr = np.asarray(source_array)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    out = _HostNDArray.__new__(_HostNDArray)
    out._jx = arr
    out._ctx = ctx or Context("cpu", 0)
    return out


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray._from_jax(
        jax.device_put(jnp.zeros(shape, _np_dtype(dtype)), ctx.jax_device()), ctx)


def ones(shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray._from_jax(
        jax.device_put(jnp.ones(shape, _np_dtype(dtype)), ctx.jax_device()), ctx)


def full(shape, val, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray._from_jax(
        jax.device_put(jnp.full(shape, val, _np_dtype(dtype)), ctx.jax_device()),
        ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    ctx = ctx or current_context()
    a = jnp.arange(start, stop, step, dtype=_np_dtype(dtype))
    if repeat > 1:
        a = jnp.repeat(a, repeat)
    return NDArray._from_jax(jax.device_put(a, ctx.jax_device()), ctx)


def concatenate(arrays, axis=0, always_copy=True):
    return NDArray._from_jax(
        jnp.concatenate([a._jx for a in arrays], axis=axis), arrays[0]._ctx)


def onehot_encode(indices, out):
    """legacy ``_onehot_encode`` (``ndarray.cc:748-867``)"""
    depth = out.shape[1]
    out._jx = jax.nn.one_hot(indices._jx.astype(jnp.int32), depth,
                             dtype=out._jx.dtype)
    return out


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0, channels=3,
             mean=None):
    """Decode an image buffer (reference ``_imdecode``), via PIL when
    present, else OpenCV (always available in this framework)."""
    import io as _io

    buf = str_img if isinstance(str_img, bytes) else str_img.encode()
    try:
        from PIL import Image

        img = Image.open(_io.BytesIO(buf))
        arr = np.asarray(img.convert("RGB" if channels == 3 else "L"),
                         dtype=np.float32)
    except ImportError:
        from .image import imdecode as _cv_imdecode

        arr = _cv_imdecode(buf, flag=1 if channels == 3 else 0)
        arr = np.asarray(arr, np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    arr = arr.transpose(2, 0, 1)[None]
    x0, y0, x1, y1 = clip_rect
    if (x0, y0, x1, y1) != (0, 0, 0, 0):
        height, width = arr.shape[2], arr.shape[3]
        if not (0 <= x0 < x1 <= width and 0 <= y0 < y1 <= height):
            raise MXNetError(
                "imdecode: clip_rect %r out of bounds for %dx%d image"
                % (clip_rect, width, height))
        arr = arr[:, :, y0:y1, x0:x1]
    if mean is not None:
        arr = arr - mean.asnumpy()
    res = array(arr)
    if out is not None:
        if not 0 <= index < out.shape[0]:
            raise MXNetError("imdecode: index %d out of range for out with "
                             "batch %d" % (index, out.shape[0]))
        if res.shape[1:] != out.shape[1:]:
            raise MXNetError("imdecode: decoded shape %r does not match out "
                             "slot shape %r" % (res.shape[1:], out.shape[1:]))
        out[index:index + 1] = res
        return out
    return res


def _imdecode(mean, index=0, x0=0, y0=0, x1=0, y1=0, n_channels=3,
              size=0, str_img=None, out=None):
    """Raw legacy ``_imdecode`` NDArray function (``ndarray.cc:832-867``),
    same argument order as the reference registration (mean, index, crop
    window, n_channels, size, image bytes): decode + crop + optional mean
    subtract, CHW float32 output.  ``mean=None`` or an empty array is the
    reference's dummy no-mean handle."""
    if str_img is None:
        raise MXNetError("_imdecode: str_img (image bytes) is required")
    return imdecode(str_img, clip_rect=(x0, y0, x1, y1), out=out, index=index,
                    channels=n_channels, mean=mean if (mean is not None and
                                                       mean.size > 0) else None)


def waitall():
    """reference MXNDArrayWaitAll — barrier on all async work."""
    (jax.device_put(0.0) + 0).block_until_ready()


# ---------------------------------------------------------------------------
# save / load — same API as reference ``nd.save/load`` (``ndarray.py:1740``)
# AND the same on-disk bytes: the dmlc magic-header stream
# (``src/ndarray/ndarray.cc:650-678``: uint64 magic 0x112 + reserved,
# vector<NDArray> [TShape(u32 ndim + u32 dims) + Context(i32 type,id) +
# i32 dtype flag + raw bytes], vector<string> names) — params files are
# byte-compatible with reference tooling in both directions.  Loading also
# auto-detects this framework's earlier .npz container.
# ---------------------------------------------------------------------------
import struct as _struct

_DMLC_MAGIC = 0x112
# reference mshadow type flags (0.9.x); 5 is unused there — claimed here as
# a bfloat16 extension so TPU-dtype arrays round-trip exactly
_FLAG_TO_DTYPE = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                  4: "int32", 5: "bfloat16"}
_DTYPE_TO_FLAG = {v: k for k, v in _FLAG_TO_DTYPE.items()}


def _write_array_segment(f, a):
    """One array's dmlc segment (ndim, shape, context, dtype flag,
    data) — the unit _save_dmlc repeats and the unit the reference's
    MXNDArraySaveRawBytes serializes alone."""
    arr = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    dname = str(a._jx.dtype) if isinstance(a, NDArray) else str(arr.dtype)
    if dname not in _DTYPE_TO_FLAG:
        raise MXNetError("save: dtype %r has no dmlc type flag" % dname)
    if dname == "bfloat16":
        arr = np.asarray(a._jx).view(np.uint16) \
            if isinstance(a, NDArray) else arr.view(np.uint16)
    f.write(_struct.pack("<I", arr.ndim))
    f.write(_struct.pack("<%dI" % arr.ndim, *arr.shape))
    f.write(_struct.pack("<ii", 1, 0))           # Context: cpu(0)
    f.write(_struct.pack("<i", _DTYPE_TO_FLAG[dname]))
    f.write(np.ascontiguousarray(arr).tobytes())


def _save_dmlc(f, names, arrays):
    f.write(_struct.pack("<QQ", _DMLC_MAGIC, 0))
    f.write(_struct.pack("<Q", len(arrays)))
    for a in arrays:
        _write_array_segment(f, a)
    f.write(_struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode()
        f.write(_struct.pack("<Q", len(b)) + b)


def _read_array_segment(rd, rdbytes):
    """Inverse of _write_array_segment (shared by _load_dmlc and
    load_from_raw_bytes)."""
    (ndim,) = rd("<I")
    shape = rd("<%dI" % ndim) if ndim else ()
    _dev_type, _dev_id = rd("<ii")
    (flag,) = rd("<i")
    dname = _FLAG_TO_DTYPE.get(flag)
    if dname is None:
        raise MXNetError("unknown dtype flag %d" % flag)
    if dname == "bfloat16":
        import jax.numpy as jnp_

        n = int(np.prod(shape)) if shape else 1
        raw = np.frombuffer(rdbytes(2 * n), np.uint16).reshape(shape)
        return array(raw.view(jnp_.bfloat16))
    dt = np.dtype(dname)
    n = int(np.prod(shape)) if shape else 1
    raw = np.frombuffer(rdbytes(dt.itemsize * n), dt).reshape(shape)
    return array(raw)


def _load_dmlc(f):
    def rdbytes(size):
        buf = f.read(size)
        if len(buf) != size:
            raise MXNetError("truncated params file")
        return buf

    def rd(fmt):
        return _struct.unpack(fmt, rdbytes(_struct.calcsize(fmt)))

    magic, _reserved = rd("<QQ")
    if magic != _DMLC_MAGIC:
        raise MXNetError("bad params magic 0x%x" % magic)
    (count,) = rd("<Q")
    arrays = []
    for _ in range(count):
        arrays.append(_read_array_segment(rd, rdbytes))
    (n_names,) = rd("<Q")
    if n_names and n_names != len(arrays):
        raise MXNetError("malformed params file: %d names for %d arrays"
                         % (n_names, len(arrays)))
    names = []
    for _ in range(n_names):
        (ln,) = rd("<Q")
        names.append(rdbytes(ln).decode())
    return names, arrays


def save(fname, data):
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    elif isinstance(data, (list, tuple)):
        names, arrays = [], list(data)
    else:
        raise MXNetError("save: need NDArray, list, or dict")
    with open(str(fname), "wb") as f:
        _save_dmlc(f, names, arrays)


def _load_path(fname):
    import os

    # np.savez appends .npz; accept either spelling on load
    for cand in (fname, str(fname) + ".npz"):
        if os.path.exists(cand):
            return cand
    raise IOError("no such file: %r" % fname)


def load(fname):
    path = _load_path(fname)
    with open(path, "rb") as f:
        head = f.read(8)
    if len(head) == 8 and _struct.unpack("<Q", head)[0] == _DMLC_MAGIC:
        with open(path, "rb") as f:
            names, arrays = _load_dmlc(f)
        if not names:
            # 0 names: a nameless list save — except 0 arrays, which is an
            # empty dict save (dict-expecting callers dominate)
            return arrays if arrays else {}
        return dict(zip(names, arrays))
    # back-compat: this framework's earlier .npz container
    with np.load(path) as f:
        keys = sorted(f.files)
        if not keys:
            return {}
        if keys[0].startswith("l:"):
            return [array(f[k]) for k in keys]
        return {k[2:]: array(f[k]) for k in keys}


def save_raw_bytes(arr):
    """Serialize ONE NDArray to bytes (reference MXNDArraySaveRawBytes /
    ``NDArray::Save`` to a string stream): the single dmlc array segment
    without the multi-array file header."""
    import io as _io

    f = _io.BytesIO()
    _write_array_segment(f, arr)
    return f.getvalue()


def load_from_raw_bytes(buf):
    """Inverse of :func:`save_raw_bytes` (reference
    MXNDArrayLoadFromRawBytes)."""
    import io as _io

    f = _io.BytesIO(bytes(buf))

    def rdbytes(size):
        b = f.read(size)
        if len(b) != size:
            raise MXNetError("truncated raw NDArray bytes")
        return b

    def rd(fmt):
        return _struct.unpack(fmt, rdbytes(_struct.calcsize(fmt)))

    return _read_array_segment(rd, rdbytes)


# ---------------------------------------------------------------------------
# op-function generation (the _init_ndarray_module analog)
# ---------------------------------------------------------------------------
def _invoke(op, args, kwargs):
    out = kwargs.pop("out", None)
    kwargs.pop("name", None)
    ctx = kwargs.pop("ctx", None)
    # split tensor kwargs (named inputs) from attr kwargs; bare numpy
    # arrays count as tensors too (the reference's CustomOp callbacks run
    # mx.nd ops on the host views they are handed)
    def _is_tensor(v):
        # 0-d numpy arrays keep filling scalar params positionally
        return isinstance(v, NDArray) or \
            (isinstance(v, np.ndarray) and v.ndim > 0)

    # tensors stay raw (numpy uncoerced) until the declared-order input
    # list is assembled, so the op's context comes from the first NDArray
    # in *declared argument order* — not call-site arg/kwarg ordering —
    # and numpy operands are then coerced onto that context
    named_inputs = {k: v for k, v in kwargs.items() if _is_tensor(v)}
    attr_kwargs = {k: v for k, v in kwargs.items() if not _is_tensor(v)}
    pos_inputs = [a for a in args if _is_tensor(a)]
    attr_args = [a for a in args if not _is_tensor(a)]
    if attr_args:
        # positional scalars fill the op's params in declaration order
        # (reference generated fns: e.g. nd.uniform(0, 1, shape=...));
        # the auto-counted variable-arity param is never positional
        ordered = [k for k in op.params if k != op.key_var_num_args]
        if len(attr_args) > len(ordered):
            raise MXNetError("%s: too many positional params (%d given, "
                             "%d exist: %s)" % (op.name, len(attr_args),
                                                len(ordered), ordered))
        for k, v in zip(ordered, attr_args):
            if k in attr_kwargs:
                raise MXNetError("%s: got multiple values for param %r"
                                 % (op.name, k))
            attr_kwargs[k] = v
    if op.key_var_num_args and op.key_var_num_args not in attr_kwargs:
        attr_kwargs[op.key_var_num_args] = len(pos_inputs) + len(named_inputs)
    attrs = op.canonicalize_attrs(attr_kwargs)
    arg_names = op.list_arguments(attrs)
    aux_names = op.list_aux_states(attrs)

    inputs = []
    aux_arrays = []
    pi = iter(pos_inputs)
    consumed_pos = 0
    for nm in arg_names:
        if nm in named_inputs:
            inputs.append(named_inputs.pop(nm))
        else:
            try:
                inputs.append(next(pi))
                consumed_pos += 1
            except StopIteration:
                raise MXNetError("%s: missing input %r" % (op.name, nm))
    for nm in aux_names:
        if nm in named_inputs:
            aux_arrays.append(named_inputs.pop(nm))
        else:
            try:
                aux_arrays.append(next(pi))
            except StopIteration:
                raise MXNetError("%s: missing aux state %r" % (op.name, nm))
    if named_inputs:
        raise MXNetError("%s: unknown input kwargs %s"
                         % (op.name, sorted(named_inputs)))
    # NB: builtins like ``sum`` are shadowed by generated op fns here
    leftover = len(list(pi))
    if leftover:
        raise MXNetError("%s: %d surplus positional NDArray input(s) "
                         "(op takes %d inputs + %d aux)"
                         % (op.name, leftover, len(arg_names),
                            len(aux_names)))
    op_ctx = next((a._ctx for a in inputs + aux_arrays
                   if isinstance(a, NDArray)), None)

    def _as_nd(v):
        return v if isinstance(v, NDArray) else array(np.asarray(v),
                                                      ctx=op_ctx)

    inputs = [_as_nd(v) for v in inputs]
    aux_arrays = [_as_nd(v) for v in aux_arrays]

    rng = _random.next_key() if op.needs_rng else None
    with _profiler.span(op.name, "imperative") as sp:
        if inputs:
            octx = op_ctx or inputs[0]._ctx  # op_ctx None => all-numpy inputs
        else:
            octx = ctx or current_context()
        # trace-time device hint: lowering decisions (Pallas vs XLA)
        # follow the op's device, not the process default backend — set
        # BEFORE the cache lookup (the jit cache keys on the device)
        tok = _reg.trace_device.set(octx.device_type)
        try:
            fn = _reg.jitted_apply(op.name, _reg.attrs_key(attrs), True)
            if inputs:
                outs, aux_up = fn([x._jx for x in inputs],
                                  [x._jx for x in aux_arrays], rng)
            else:
                with jax.default_device(octx.jax_device()):
                    outs, aux_up = fn([], [], rng)
        finally:
            _reg.trace_device.reset(tok)
        sp.sync(outs)
    # write aux updates back (reference mutates aux NDArrays in the op)
    for arr, new in zip(aux_arrays, aux_up or []):
        arr._jx = new
    results = [NDArray._from_jax(o, octx) for o in outs]
    if out is not None:
        outs_list = [out] if isinstance(out, NDArray) else list(out)
        for dst, src in zip(outs_list, results):
            dst._jx = src._jx
        return out
    return results[0] if len(results) == 1 else results


def _make_op_func(op_name):
    op = _reg.get(op_name)

    def fn(*args, **kwargs):
        return _invoke(op, args, kwargs)

    fn.__name__ = op_name
    fn.__doc__ = op.doc or ("TPU-native op %r (see mxnet_tpu.ops)" % op_name)
    return fn


def _init_ndarray_module():
    mod = sys.modules[__name__]
    for name in _reg.list_ops():
        if not hasattr(mod, name):
            setattr(mod, name, _make_op_func(name))


_init_ndarray_module()


def __getattr__(name):
    # ops registered AFTER import (registry.register in user code)
    # resolve lazily, so late registration behaves like the built-ins
    try:
        _reg.get(name)
    except MXNetError:
        raise AttributeError("module %r has no attribute %r"
                             % (__name__, name)) from None
    fn = _make_op_func(name)
    setattr(sys.modules[__name__], name, fn)
    return fn
