"""Executor — bind a Symbol and run forward/backward.

Reference: ``src/executor/graph_executor.cc`` + ``python/mxnet/executor.py``
(SURVEY §3.1).  The reference builds a full fwd+bwd nnvm graph, plans memory,
and pushes one engine op per node.  TPU-native collapse: the WHOLE symbol
traces into ONE jitted XLA computation —

* Gradient pass (``graph_executor.cc:219``)      -> ``jax.vjp``
* InferShape/InferType (``:413``)                -> ``jax.eval_shape`` tracing
* PlanMemory / InitDataEntryMemory (``:425``)    -> XLA buffer assignment
* InitCachedOps / bulk segments (``:544,678``)   -> the jit cache itself
* engine var-dependency scheduling               -> XLA dataflow + PJRT async

``forward(is_train=True)`` runs ONE fused fwd+bwd XLA computation (with
default all-ones head gradients — loss ops ignore them by design, matching
``backward()`` with no out_grads) and stashes the gradients;
``backward()`` then just applies them honoring grad_req.  This mirrors the
reference executor's single cached fwd+bwd graph (``InitCachedOps``) and is
the TPU-optimal shape: one compiled step, no residual round-trips.  An
explicit ``backward(out_grads)`` re-runs the fused computation with those
cotangents (rare, non-loss graphs).

grad_req semantics ('write'/'add'/'null') follow ``include/mxnet/op_attr_types.h``
kWriteTo/kAddTo/kNullOp; 'add' accumulates into the bound grad arrays.
"""

from __future__ import annotations

import logging
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from . import compile_cache as _compile_cache
from . import perfdebug as _perfdebug
from . import profiler as _profiler
from . import random as _random
from . import telemetry as _telemetry
from .base import MXNetError
from .context import Context
from .ndarray import NDArray, zeros as nd_zeros
from .ops import registry as _ops_registry


class _DeviceHintFn:
    """Wraps an executor's jitted step so tracing (first call, or .lower)
    runs with ``ops.registry.trace_device`` set to the executor's device —
    device-dependent lowering (Pallas vs XLA) must follow the
    computation's device, not the process-wide default backend.

    ``compile_note`` (a kind string, set only when telemetry is enabled at
    build time) times the FIRST call — which pays jax tracing + XLA
    compilation synchronously — into the ``xla.compile.*`` metrics;
    ``attrib`` (``(exec_name, kind_name)``, set when
    :mod:`mxnet_tpu.perfdebug` attribution OR
    :mod:`mxnet_tpu.compile_cache` manifest recording is enabled at
    build time) additionally captures the first call's
    compiled-executable cost / memory / HLO fingerprint and/or records
    the build's replayable identity (kind + abstract signature) into the
    compile-once warm-up registry.  After the first call the wrapper is
    a single attribute check per dispatch."""

    def __init__(self, fn, dev_type, compile_note=None, attrib=None,
                 kind=None):
        self._fn = fn
        self._dev = dev_type
        self._note = compile_note
        self._attrib = attrib
        self._kind = kind

    def __call__(self, *args, **kwargs):
        if self._note is not None or self._attrib is not None:
            return self._first_call(args, kwargs)
        tok = _ops_registry.trace_device.set(self._dev)
        try:
            return self._fn(*args, **kwargs)
        finally:
            _ops_registry.trace_device.reset(tok)

    def _first_call(self, args, kwargs):
        note, self._note = self._note, None
        attrib, self._attrib = self._attrib, None
        tok = _ops_registry.trace_device.set(self._dev)
        t0 = time.perf_counter()
        try:
            return self._fn(*args, **kwargs)
        finally:
            _ops_registry.trace_device.reset(tok)
            dt = time.perf_counter() - t0
            if note is not None:
                _telemetry.inc("xla.compile.seconds", dt, kind=note)
                _telemetry.observe("xla.compile.first_call_seconds", dt,
                                   kind=note)
            if attrib is not None:
                # shapes/dtypes only (aval metadata survives donation);
                # neither hook ever raises into the step
                if _perfdebug.enabled():
                    _perfdebug.capture(attrib[0], attrib[1], self.lower,
                                       args, kwargs)
                if _compile_cache.recording():
                    _compile_cache.note_build(
                        attrib[0], self._kind if self._kind is not None
                        else attrib[1], self.lower, args, kwargs, dt)

    def lower(self, *args, **kwargs):
        tok = _ops_registry.trace_device.set(self._dev)
        try:
            return self._fn.lower(*args, **kwargs)
        finally:
            _ops_registry.trace_device.reset(tok)

__all__ = ["Executor"]


# ops whose outputs are NOT worth recomputing under mirror mode — the
# FLOP-heavy set the reference's mirror predicate also skips
# (graph_executor.cc:205-219: MXNET_BACKWARD_DO_MIRROR recomputes cheap
# activations in backward instead of storing them)
_MIRROR_SKIP = frozenset({
    "Convolution", "Deconvolution", "FullyConnected", "dot", "batch_dot",
    "RNN", "MultiHeadAttention", "FlashAttention", "Correlation",
    "Embedding", "Custom", "_Native", "_NDArray",
})


def _mirror_mode():
    """0 = off; 1 = segment remat between FLOP anchors; 2 = whole-graph
    remat saving only matmul/conv outputs (max memory savings, ~1/3 more
    FLOPs — the deep end of the reference's mirror trade)."""
    import os

    # lint: ok[tracer-purity] read at trace time BY DESIGN — the executor keys its fn cache on trace_env_fingerprint(), so a changed value retraces
    v = os.environ.get("MXNET_BACKWARD_DO_MIRROR", "")
    if v in ("", "0"):
        return 0
    if v in ("2", "dots", "full"):
        return 2
    return 1


def _mirror_enabled():
    return _mirror_mode() != 0


def _dots_and_convs_saveable(prim, *_args, **_params):
    return prim.name in ("dot_general", "conv_general_dilated")


def _graph_forward(symbol, arg_vals, aux_vals, is_train, rng):
    """Trace the symbol DAG; returns (outputs list, new_aux dict).

    Under ``MXNET_BACKWARD_DO_MIRROR`` (read at trace time) training
    forwards are traced with segment-level rematerialization: maximal runs
    of cheap ops between FLOP-heavy anchors execute inside one
    ``jax.checkpoint``, so only segment *inputs* stay live across
    fwd/bwd — the activations inside a run (BN/activation/pad/... chains)
    are recomputed during backward, exactly the reference's mirror trade
    (``graph_executor.cc:205-219``).
    """
    nodes = symbol._nodes()
    mode = _mirror_mode() if is_train else 0
    if mode == 1:
        return _graph_forward_mirror(symbol, nodes, arg_vals, aux_vals, rng)
    if mode == 2:
        def whole(av, xv):
            return _graph_forward_plain(symbol, nodes, av, xv, True, rng)

        return jax.checkpoint(whole, policy=_dots_and_convs_saveable)(
            arg_vals, aux_vals)
    return _graph_forward_plain(symbol, nodes, arg_vals, aux_vals,
                                is_train, rng)


def _bn_relu_peephole(symbol, nodes):
    """BatchNorm nodes whose SOLE consumer is a relu ``Activation`` fuse
    into one kernel application (stats+normalize+relu in a single HBM
    pass via ops/bn_pallas.py) — the executor-level analog of cuDNN's
    fused BN-activation.  Returns ({id(bn)}, {id(act): bn_node})."""
    count = {}
    for node in nodes:
        if node.is_variable:
            continue
        for c, ci in node.inputs:
            k = (id(c), ci)
            count[k] = count.get(k, 0) + 1
    for n, i in symbol._outputs:
        k = (id(n), i)
        count[k] = count.get(k, 0) + 1  # graph outputs must materialize
    bn_defer, act_fuse = set(), {}
    for node in nodes:
        if node.is_variable or node.op is None \
                or node.op.name != "Activation" \
                or node.attrs.get("act_type") != "relu":
            continue
        child, ci = node.inputs[0]
        if ci != 0 or child.is_variable or child.op is None \
                or child.op.name != "BatchNorm":
            continue
        a = child.attrs
        if a.get("use_global_stats") or a.get("output_mean_var"):
            continue
        if count.get((id(child), 0), 0) != 1:
            continue
        bn_defer.add(id(child))
        act_fuse[id(node)] = child
    return bn_defer, act_fuse


def _graph_forward_plain(symbol, nodes, arg_vals, aux_vals, is_train, rng):
    from .ops.nn import _batch_norm as _bn_apply

    entry_val = {}
    new_aux = {}
    bn_defer, act_fuse = _bn_relu_peephole(symbol, nodes) \
        if is_train else (set(), {})
    bn_stash = {}
    for ni, node in enumerate(nodes):
        if node.is_variable:
            if node.name in arg_vals:
                entry_val[(id(node), 0)] = arg_vals[node.name]
            elif node.name in aux_vals:
                entry_val[(id(node), 0)] = aux_vals[node.name]
            else:
                raise MXNetError("unbound variable %r" % node.name)
            continue
        op = node.op
        na = node.num_args()
        if id(node) in bn_defer:
            # computed inside the consuming relu Activation's slot
            bn_stash[id(node)] = (
                [entry_val[(id(c), ci)] for c, ci in node.inputs[:na]],
                [entry_val[(id(c), ci)] for c, ci in node.inputs[na:]])
            continue
        if id(node) in act_fuse:
            bn_node = act_fuse[id(node)]
            bn_ins, bn_auxs = bn_stash[id(bn_node)]
            outs, aux_up = _bn_apply(bn_node.attrs, bn_ins, bn_auxs,
                                     True, None, act_type="relu")
            entry_val[(id(node), 0)] = outs[0]
            if aux_up is not None:
                na_bn = bn_node.num_args()
                for (child, _ci), new in zip(bn_node.inputs[na_bn:],
                                             aux_up):
                    new_aux[child.name] = new
            continue
        ins = [entry_val[(id(c), ci)] for c, ci in node.inputs[:na]]
        auxs = [entry_val[(id(c), ci)] for c, ci in node.inputs[na:]]
        key = jax.random.fold_in(rng, ni) if op.needs_rng else None
        outs, aux_up = op.apply(node.attrs, ins, auxs, is_train, key)
        for i, o in enumerate(outs):
            entry_val[(id(node), i)] = o
        if aux_up is not None:
            for (child, _ci), new in zip(node.inputs[na:], aux_up):
                new_aux[child.name] = new
    outputs = [entry_val[(id(n), i)] for n, i in symbol._outputs]
    return outputs, new_aux


def _graph_forward_mirror(symbol, nodes, arg_vals, aux_vals, rng,
                          max_seg=32):
    """Mirror-mode trace: greedy segments of non-anchor ops under one
    ``jax.checkpoint`` each."""
    entry_val = {}
    new_aux = {}

    def run_nodes(node_list, local):
        """Execute (node, ni) list against the ``local`` entry map; returns
        (per-node outs, per-node aux_up)."""
        outs_all, aux_all = [], []
        for node, ni in node_list:
            op = node.op
            na = node.num_args()
            ins = [local[(id(c), ci)] for c, ci in node.inputs[:na]]
            auxs = [local[(id(c), ci)] for c, ci in node.inputs[na:]]
            key = jax.random.fold_in(rng, ni) if op.needs_rng else None
            outs, aux_up = op.apply(node.attrs, ins, auxs, True, key)
            for i, o in enumerate(outs):
                local[(id(node), i)] = o
            outs_all.append(list(outs))
            aux_all.append(list(aux_up) if aux_up is not None else None)
        return outs_all, aux_all

    def record(node_list, outs_all, aux_all):
        for (node, _ni), outs, aux_up in zip(node_list, outs_all, aux_all):
            for i, o in enumerate(outs):
                entry_val[(id(node), i)] = o
            if aux_up is not None:
                na = node.num_args()
                for (child, _ci), new in zip(node.inputs[na:], aux_up):
                    new_aux[child.name] = new

    def flush(segment):
        if not segment:
            return
        in_seg = {id(n) for n, _ in segment}
        ext = []
        seen = set()
        for node, _ni in segment:
            for c, ci in node.inputs:
                k = (id(c), ci)
                if id(c) not in in_seg and k not in seen:
                    seen.add(k)
                    ext.append(k)
        ext_vals = [entry_val[k] for k in ext]

        def seg_fn(vals):
            return run_nodes(segment, dict(zip(ext, vals)))

        outs_all, aux_all = jax.checkpoint(seg_fn)(ext_vals)
        record(segment, outs_all, aux_all)

    segment = []
    for ni, node in enumerate(nodes):
        if node.is_variable:
            flush(segment)
            segment = []
            if node.name in arg_vals:
                entry_val[(id(node), 0)] = arg_vals[node.name]
            elif node.name in aux_vals:
                entry_val[(id(node), 0)] = aux_vals[node.name]
            else:
                raise MXNetError("unbound variable %r" % node.name)
        elif node.op.name in _MIRROR_SKIP:
            flush(segment)
            segment = []
            outs_all, aux_all = run_nodes([(node, ni)], entry_val)
            record([(node, ni)], outs_all, aux_all)
        else:
            segment.append((node, ni))
            if len(segment) >= max_seg:
                flush(segment)
                segment = []
    flush(segment)
    outputs = [entry_val[(id(n), i)] for n, i in symbol._outputs]
    return outputs, new_aux


def _nonfinite_expr(values):
    """Trace-time helper: ONE fused logical-or over every floating leaf —
    ``True`` iff any value contains NaN/Inf.  This is the in-graph NaN
    guard reduction the train kinds fold into the step (docs/resilience.md):
    the host reads a single scalar instead of pulling every output and
    gradient."""
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(v))) for v in values
             if jnp.issubdtype(v.dtype, jnp.floating)]
    if not flags:
        return jnp.zeros((), jnp.bool_)
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


_ANY_NONFINITE_JIT = None


def any_nonfinite(values):
    """One jitted logical-or reduction over ``values`` (device arrays) →
    python bool.  The sync is a single scalar transfer; the per-array
    reductions run on device.  Used by the NaN-guard fallback for
    executors without an accumulated in-graph flag (e.g. after a fault
    injection poisoned gradients out-of-graph)."""
    vals = [v for v in values if jnp.issubdtype(v.dtype, jnp.floating)]
    if not vals:
        return False
    global _ANY_NONFINITE_JIT
    if _ANY_NONFINITE_JIT is None:
        _ANY_NONFINITE_JIT = jax.jit(_nonfinite_expr)
    return bool(_ANY_NONFINITE_JIT(vals))


def _global_norm_expr(values):
    """Trace-time helper: one fused sum-of-squares over every floating
    leaf → the global L2 norm as an f32 scalar.  Math in f32 so bf16
    gradients don't overflow the square."""
    total = jnp.zeros((), jnp.float32)
    for v in values:
        total = total + jnp.sum(jnp.square(v.astype(jnp.float32)))
    return jnp.sqrt(total)


_GLOBAL_NORM_JIT = None


def global_norm(values):
    """One jitted global-L2-norm reduction over ``values`` (device
    arrays) → python float; single scalar transfer like
    :func:`any_nonfinite`.  The statistic the training sentinel's
    ``anomaly_policy`` z-scores (docs/resilience.md "Statistical
    anomaly rollback")."""
    vals = [v for v in values if jnp.issubdtype(v.dtype, jnp.floating)]
    if not vals:
        return 0.0
    global _GLOBAL_NORM_JIT
    if _GLOBAL_NORM_JIT is None:
        _GLOBAL_NORM_JIT = jax.jit(_global_norm_expr)
    return float(_GLOBAL_NORM_JIT(vals))


def _kind_name(kind):
    """Human name of an executor program kind: the kind string itself,
    or a tuple kind's head (``("train_sgd", ...)`` -> ``"train_sgd"``,
    placement segments -> ``"seg"``)."""
    if isinstance(kind, str):
        return kind
    if kind[0] == "seg":
        return "seg"
    return str(kind[0])


def sgd_step_math(p, g, mom, lr, wd, momentum, rescale, clip):
    """One SGD(-momentum) parameter step, math in f32, result cast back to
    the stored dtype (bf16 params stay bf16).  Shared by the two-dispatch
    fused update (Module._try_fused_update) and the single-dispatch
    ``train_sgd`` executor kind so their numerics can never diverge.
    Returns (new_p, new_mom_or_None)."""
    g = g.astype(jnp.float32) * rescale
    if clip > 0:
        g = jnp.clip(g, -clip, clip)
    g = g + wd * p.astype(jnp.float32)
    if momentum != 0.0:
        m = momentum * mom.astype(jnp.float32) - lr * g
        return (p.astype(jnp.float32) + m).astype(p.dtype), \
            m.astype(mom.dtype)
    return (p.astype(jnp.float32) - lr * g).astype(p.dtype), None


class Executor:
    """reference ``python/mxnet/executor.py:25``"""

    def __init__(self, symbol, ctx, arg_dict, grad_dict, grad_req, aux_dict,
                 group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()
        self.arg_dict = arg_dict
        self.grad_dict = grad_dict
        self.aux_dict = aux_dict
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(self.arg_names, grad_req))
        self.grad_req = {n: grad_req.get(n, "null") for n in self.arg_names}
        self._group2ctx = group2ctx or {}
        self.outputs = []
        self._monitor_callback = None
        self._pending_grads = None
        self._last_state = None
        self._rng_step = 0
        self._fns = {}
        self._build_counts = {}  # program identity -> build count
        self._needs_rng = None
        self._rng_cache = None
        self._seg_chain = None
        self._global_mesh = None  # set by Module in multi-process mode
        self._spmd_mesh = None    # set by Module for single-process meshes
        # in-graph NaN guard (Module._install_nan_guard): train kinds fold
        # a logical-or reduction over outputs+grads into the step and
        # accumulate it here as a device scalar — read via
        # consume_nan_flag() at the caller's cadence, no per-batch pulls
        self._nan_guard = False
        self._nan_acc = None    # accumulated device flag (or None)
        self._nan_batch = None  # THIS batch's flag (gates metric stats)
        self._nan_stale = False  # out-of-graph grad mutation invalidated it
        self._nan_false = None  # cached device False scalar
        self._init_placement()

    arg_arrays = property(lambda s: [s.arg_dict[n] for n in s.arg_names])
    grad_arrays = property(lambda s: [s.grad_dict.get(n) for n in s.arg_names])
    aux_arrays = property(lambda s: [s.aux_dict[n] for n in s.aux_names])

    # -- jitted graph functions ------------------------------------------
    def _symbol_name(self):
        outs = self.output_names
        return outs[0].rsplit("_output", 1)[0] if outs else "exec"

    def _diff_names(self):
        return [n for n in self.arg_names if self.grad_req[n] != "null"]

    def _note_build(self, kind):
        """Record one jitted-program build (``xla.compile.count``) and run
        the recompilation detector.

        Builds are counted per program *identity* — the executor kind
        (``predict``/``train``/``train_sgd``/...; placement segments key
        on ``(seg, index, is_train)``) with tuple-kind parameters and the
        env fingerprint stripped — so each program's legitimate first
        build counts once and only REbuilds of the same identity
        accumulate: hyperparameters baked into a fused-step cache key,
        env-fingerprint flips.  An identity built more than
        ``MXNET_RECOMPILE_WARN_THRESHOLD`` times (default 8, 0 disables)
        warns with the executor's name and bumps
        ``xla.recompile_warnings``; a many-segment executor compiling
        everything exactly once never trips it.  Returns the telemetry
        compile-note for :class:`_DeviceHintFn` first-call timing (None
        when disabled)."""
        kind_name = _kind_name(kind)
        if isinstance(kind, str):
            ident = kind
        elif kind[0] == "seg":  # ("seg", si, is_train, fingerprint)
            ident = kind[:3]
        else:
            ident = kind_name
        builds = self._build_counts[ident] = \
            self._build_counts.get(ident, 0) + 1
        limit = int(os.environ.get("MXNET_RECOMPILE_WARN_THRESHOLD", "8"))
        if 0 < limit < builds:
            logging.warning(
                "executor %r compiled its %r program %d times (threshold "
                "%d): recompilation churn — per-step hyperparameter "
                "changes or env-fingerprint flips retrace/recompile every "
                "time (MXNET_RECOMPILE_WARN_THRESHOLD tunes this).%s",
                self._symbol_name(), kind_name, builds, limit,
                " Rebuilds are served from the persistent compile cache "
                "(cheap loads, but the retrace cost remains)."
                if _compile_cache.enabled() else
                " MXNET_COMPILE_CACHE_DIR would at least make the "
                "rebuilds persistent-cache loads instead of full "
                "compiles.")
            _telemetry.inc("xla.recompile_warnings")
        if not _telemetry.enabled():
            return None
        _telemetry.inc("xla.compile.count", kind=kind_name)
        return kind_name

    def _get_fn(self, kind):
        # keyed on the trace-time env fingerprint: MXNET_BN_*/mirror/
        # barrier toggles must retrace, not silently reuse a stale jit
        cache_key = (kind, _ops_registry.trace_env_fingerprint())
        if cache_key in self._fns:
            # IN-PROCESS jit function reuse — split from the on-disk
            # xla.compile.persistent_cache_hits (compile_cache.py)
            _telemetry.inc("xla.compile.fn_cache_hits")
            return self._fns[cache_key]
        symbol = self._symbol
        arg_names = list(self.arg_names)
        aux_names = list(self.aux_names)
        diff_names = self._diff_names()

        def _vjp_parts(args, aux, rng):
            amap = dict(zip(arg_names, args))
            axmap = dict(zip(aux_names, aux))
            nondiff = {n: v for n, v in amap.items() if n not in diff_names}

            def g(diff_args):
                vals = dict(nondiff)
                vals.update(diff_args)
                outs, new_aux = _graph_forward(symbol, vals, axmap, True, rng)
                return tuple(outs), new_aux

            outs, vjp_fn, new_aux = jax.vjp(
                g, {n: amap[n] for n in diff_names}, has_aux=True)
            new_aux_list = [new_aux.get(n, axmap[n]) for n in aux_names]
            return outs, new_aux_list, vjp_fn

        if kind == "predict":
            def f(args, aux, rng):
                outs, _ = _graph_forward(
                    symbol, dict(zip(arg_names, args)),
                    dict(zip(aux_names, aux)), False, rng)
                return outs

            fn = jax.jit(f)
        elif kind == "train":
            # fused fwd+bwd with default (ones) head grads — one XLA step
            def f(args, aux, rng):
                outs, new_aux_list, vjp_fn = _vjp_parts(args, aux, rng)
                (grads,) = vjp_fn(tuple(jnp.ones_like(o) for o in outs))
                return list(outs), new_aux_list, grads

            fn = jax.jit(f)
        elif kind == "train_guard":
            # fused fwd+bwd + in-graph NaN guard: one extra scalar output
            # or-accumulating non-finiteness of outputs+grads into the
            # carried flag (replaces the per-gradient asnumpy() loop)
            def f(args, aux, rng, nan_acc):
                outs, new_aux_list, vjp_fn = _vjp_parts(args, aux, rng)
                (grads,) = vjp_fn(tuple(jnp.ones_like(o) for o in outs))
                flag = _nonfinite_expr(
                    list(outs) + [grads[n] for n in diff_names])
                return (list(outs), new_aux_list, grads,
                        jnp.logical_or(nan_acc, flag), flag)

            fn = jax.jit(f)
        elif kind == "train_fwd":
            # forward-only in train mode (aux updates, no grads) — used when
            # the caller never calls backward (e.g. Monitor probing)
            def f(args, aux, rng):
                outs, new_aux = _graph_forward(
                    symbol, dict(zip(arg_names, args)),
                    dict(zip(aux_names, aux)), True, rng)
                new_aux_list = [new_aux.get(n, ax)
                                for n, ax in zip(aux_names, aux)]
                return outs, new_aux_list

            fn = jax.jit(f)
        elif kind == "train_with_grads":
            # explicit head cotangents (non-loss graphs)
            def f(args, aux, rng, out_grads):
                outs, new_aux_list, vjp_fn = _vjp_parts(args, aux, rng)
                (grads,) = vjp_fn(tuple(out_grads))
                return list(outs), new_aux_list, grads

            fn = jax.jit(f)
        elif isinstance(kind, tuple) and kind[0] == "train_sgd":
            # ONE dispatch for fwd+bwd+SGD(-momentum) update with donated
            # param/momentum buffers — the whole training step is a single
            # XLA computation (the reference's bulk-segment idea taken to
            # its TPU conclusion).  Hyperparameters are baked into the
            # compiled step; Module caches per hyper-tuple.  With
            # ``guard`` the step also folds the NaN-guard reduction in: a
            # non-finite batch's param/momentum update is withheld
            # in-graph (jnp.where on the batch flag — the fused step
            # never applies a poisoned update) and the flag or-accumulates
            # into the carried scalar for the host's lazy read.
            _, upd_names_t, momentum, rescale, clip, guard = kind
            upd_names = list(upd_names_t)
            other_names = [n for n in arg_names if n not in upd_names_t]

            def _step_core(upd_vals, other_vals, aux, rng, moms, lrs, wds):
                amap = dict(zip(upd_names, upd_vals))
                amap.update(zip(other_names, other_vals))
                args = [amap[n] for n in arg_names]
                outs, new_aux_list, vjp_fn = _vjp_parts(args, aux, rng)
                (grads,) = vjp_fn(tuple(jnp.ones_like(o) for o in outs))
                new_p, new_m = [], []
                for i, n in enumerate(upd_names):
                    p, m = sgd_step_math(
                        amap[n], grads[n], moms[i] if momentum != 0.0
                        else None, lrs[i], wds[i], momentum, rescale, clip)
                    new_p.append(p)
                    if m is not None:
                        new_m.append(m)
                grad_list = [grads[n] for n in upd_names]
                return list(outs), new_aux_list, new_p, new_m, grad_list

            if guard:
                def f(upd_vals, other_vals, aux, rng, moms, lrs, wds,
                      nan_acc):
                    outs, new_aux_list, new_p, new_m, grad_list = \
                        _step_core(upd_vals, other_vals, aux, rng, moms,
                                   lrs, wds)
                    flag = _nonfinite_expr(outs + grad_list)
                    new_p = [jnp.where(flag, p0, p1)
                             for p0, p1 in zip(upd_vals, new_p)]
                    new_m = [jnp.where(flag, m0, m1)
                             for m0, m1 in zip(moms, new_m)]
                    return (outs, new_aux_list, new_p, new_m, grad_list,
                            jnp.logical_or(nan_acc, flag), flag)
            else:
                f = _step_core

            fn = jax.jit(f, donate_argnums=(0, 4))
        elif isinstance(kind, tuple) and kind[0] == "train_sgd_mesh":
            # the ZeRO variant of train_sgd (kvstore='mesh', PAPERS.md
            # "Automatic Cross-Replica Sharding of Weight Update"):
            # eligible params' updates shard over the mesh batch axis —
            # the batch-summed gradient is consumed row-sharded (GSPMD
            # lowers the would-be all-reduce to a reduce-scatter), each
            # device updates only its momentum/param rows, and the new
            # rows all-gather back into the replicated parameter.  Full
            # gradients are never materialized, so this kind returns no
            # grad_list (grad_dict goes stale, like the scan kind).
            (_, upd_names_t, zero_names_t, momentum, rescale, clip,
             guard, axis) = kind
            from .kvstore_mesh import mesh_param_step

            mesh = self._spmd_mesh
            if mesh is None:
                raise MXNetError(
                    "train_sgd_mesh requires a mesh-bound executor")
            upd_names = list(upd_names_t)
            zero_set = frozenset(zero_names_t)
            other_names = [n for n in arg_names if n not in upd_names_t]
            # the per-param dispatch + layout pinning is the SHARED
            # helper, so this kind and Module's two-dispatch fused
            # update can never diverge numerically
            mstep = mesh_param_step(mesh, momentum, rescale, clip,
                                    zero_names_t, guard=guard,
                                    axis_name=axis)

            def _mesh_core(upd_vals, other_vals, aux, rng, moms, lrs,
                           wds):
                amap = dict(zip(upd_names, upd_vals))
                amap.update(zip(other_names, other_vals))
                args = [amap[n] for n in arg_names]
                outs, new_aux_list, vjp_fn = _vjp_parts(args, aux, rng)
                (grads,) = vjp_fn(tuple(jnp.ones_like(o) for o in outs))
                new_p, new_m, zflags, plain_grads = [], [], [], []
                for i, n in enumerate(upd_names):
                    m_in = moms[i] if momentum != 0.0 else None
                    p, m, zf = mstep(n, amap[n], grads[n], m_in, lrs[i],
                                     wds[i])
                    if zf is not None:
                        zflags.append(zf)
                    elif n not in zero_set:
                        plain_grads.append(grads[n])
                    new_p.append(p)
                    if m is not None:
                        new_m.append(m)
                return list(outs), new_aux_list, new_p, new_m, zflags, \
                    plain_grads

            if guard:
                def f(upd_vals, other_vals, aux, rng, moms, lrs, wds,
                      nan_acc):
                    (outs, new_aux_list, new_p, new_m, zflags,
                     plain_grads) = _mesh_core(upd_vals, other_vals, aux,
                                               rng, moms, lrs, wds)
                    # unsharded residue checks its full grads; the ZeRO
                    # params' flags were psum'd from the scattered rows
                    flag = _nonfinite_expr(outs + plain_grads)
                    for zf in zflags:
                        flag = jnp.logical_or(flag, zf)
                    new_p = [jnp.where(flag, p0, p1)
                             for p0, p1 in zip(upd_vals, new_p)]
                    new_m = [jnp.where(flag, m0, m1)
                             for m0, m1 in zip(moms, new_m)]
                    return (outs, new_aux_list, new_p, new_m,
                            jnp.logical_or(nan_acc, flag), flag)
            else:
                def f(upd_vals, other_vals, aux, rng, moms, lrs, wds):
                    outs, new_aux_list, new_p, new_m, _zf, _pg = \
                        _mesh_core(upd_vals, other_vals, aux, rng, moms,
                                   lrs, wds)
                    return outs, new_aux_list, new_p, new_m

            fn = jax.jit(f, donate_argnums=(0, 4))
        elif isinstance(kind, tuple) and kind[0] == "train_sgd_scan":
            # K full train steps inside ONE dispatch: lax.scan over stacked
            # input batches with params/momenta/aux as carry.  The
            # reference bulks engine ops into segments to cut dispatch
            # overhead (``graph_executor.cc:678`` InitOpSegs /
            # MXNET_EXEC_BULK_EXEC_TRAIN); on a tunneled TPU the per-step
            # dispatch round trip is tens of ms, so bulking across steps
            # is the same trade one level up.
            (_, upd_names_t, scan_names_t, momentum, rescale, clip,
             collect) = kind
            upd_names = list(upd_names_t)
            scan_names = list(scan_names_t)
            static_names = [n for n in arg_names
                            if n not in upd_names_t and n not in scan_names_t]

            def f(upd_vals, static_vals, aux, rng, moms, lrs, wds, stacks):
                def body(carry, xs):
                    cur_p, cur_m, cur_aux, cur_rng = carry
                    amap = dict(zip(upd_names, cur_p))
                    amap.update(zip(static_names, static_vals))
                    amap.update(zip(scan_names, xs))
                    args = [amap[n] for n in arg_names]
                    outs, new_aux_list, vjp_fn = _vjp_parts(
                        args, cur_aux, cur_rng)
                    (grads,) = vjp_fn(tuple(jnp.ones_like(o) for o in outs))
                    new_p, new_m = [], []
                    for i, n in enumerate(upd_names):
                        p, m = sgd_step_math(
                            amap[n], grads[n], cur_m[i] if momentum != 0.0
                            else None, lrs[i], wds[i], momentum, rescale,
                            clip)
                        new_p.append(p)
                        if m is not None:
                            new_m.append(m)
                    nxt_rng = jax.random.fold_in(cur_rng, 1)
                    # collect=False skips the K-step output stack — at
                    # PTB shapes the stacked softmax (K, N*T, vocab) is
                    # GBs of HBM nobody reads (b256/bulk-80 OOM'd 27 GB)
                    return ((new_p, new_m, new_aux_list, nxt_rng),
                            list(outs) if collect else None)

                (new_p, new_m, new_aux_list, _), outs_stack = jax.lax.scan(
                    body, (list(upd_vals), list(moms), list(aux), rng),
                    list(stacks))
                return outs_stack, new_aux_list, new_p, new_m

            fn = jax.jit(f, donate_argnums=(0, 4))
        elif isinstance(kind, tuple) and kind[0] == "predict_scan":
            # K inference forwards in ONE dispatch (lax.scan over stacked
            # inputs) — the serving-throughput analog of train_sgd_scan
            _, scan_names_t = kind
            scan_names = list(scan_names_t)
            static_names = [n for n in arg_names if n not in scan_names_t]

            def f(static_vals, aux, rng, stacks):
                axmap = dict(zip(aux_names, aux))

                def body(carry, xs):
                    amap = dict(zip(static_names, static_vals))
                    amap.update(zip(scan_names, xs))
                    outs, _ = _graph_forward(symbol, amap, axmap, False,
                                             rng)
                    return carry, list(outs)

                _, outs_stack = jax.lax.scan(body, 0, list(stacks))
                return outs_stack

            fn = jax.jit(f)
        else:
            raise ValueError(kind)
        attrib = (self._symbol_name(), _kind_name(kind)) \
            if _perfdebug.enabled() or _compile_cache.recording() else None
        fn = _DeviceHintFn(fn, self._ctx.device_type,
                           self._note_build(kind), attrib, kind=kind)
        self._fns[cache_key] = fn
        return fn

    # -- group2ctx placement (model parallelism) --------------------------
    def _init_placement(self):
        """The ``PlaceDevice`` pass analog (reference
        ``graph_executor.cc:231-305`` + ``src/operator/cross_device_copy.cc``).

        Nodes annotated with a ``ctx_group`` attr (``mx.AttrScope``) are
        assigned the mapped context; variables adopt their first consumer's
        context (reference ``AssignContext``), and parameter / gradient /
        aux NDArrays are MOVED onto those devices at bind time.  Execution
        then runs as per-device jitted *segments* — maximal topo runs on
        one device — with ``jax.device_put`` at segment boundaries playing
        the ``_CrossDeviceCopy`` role.  When every group maps to the bind
        context the plan collapses and the whole-graph single-jit fast
        path is used."""
        self._segments = None
        if not self._group2ctx:
            return
        nodes = self._symbol._nodes()
        base = self._ctx
        dev_of = {}
        distinct = False
        for node in nodes:
            if node.is_variable:
                continue
            g = node.misc_attr.get("ctx_group")
            ctx = self._group2ctx.get(g, base) if g is not None else base
            dev_of[id(node)] = ctx
            if ctx.jax_device() != base.jax_device():
                distinct = True
        if not distinct:
            return
        # variables adopt the context of their first consumer
        for node in nodes:
            if node.is_variable:
                continue
            for child, _ci in node.inputs:
                if child.is_variable and id(child) not in dev_of:
                    dev_of[id(child)] = dev_of[id(node)]
        for node in nodes:
            if node.is_variable and id(node) not in dev_of:
                dev_of[id(node)] = base
        name2ctx = {n.name: dev_of[id(n)] for n in nodes if n.is_variable}
        for group in (self.arg_dict, self.aux_dict, self.grad_dict):
            for n, arr in group.items():
                ctx = name2ctx.get(n)
                if ctx is not None and \
                        arr._ctx.jax_device() != ctx.jax_device():
                    arr._jx = jax.device_put(arr._jx, ctx.jax_device())
                    arr._ctx = ctx
        # maximal same-device topo runs of compute nodes
        ni_of = {id(n): i for i, n in enumerate(nodes)}
        segs = []
        for node in nodes:
            if node.is_variable:
                continue
            d = dev_of[id(node)]
            if segs and segs[-1][0].jax_device() == d.jax_device():
                segs[-1][1].append(node)
            else:
                segs.append((d, [node]))
        # per-segment IO: external entries consumed / entries needed later
        produced_by = {}
        for si, (_d, seg_nodes) in enumerate(segs):
            for n in seg_nodes:
                produced_by[id(n)] = si
        seg_io = []
        out_entries = {(id(n), i) for n, i in self._symbol._outputs}
        for si, (_d, seg_nodes) in enumerate(segs):
            in_keys, seen = [], set()
            for n in seg_nodes:
                for c, ci in n.inputs:
                    k = (id(c), ci)
                    if produced_by.get(id(c)) == si:
                        continue
                    if k not in seen:
                        seen.add(k)
                        in_keys.append(k)
            seg_io.append([in_keys, None])
        consumers = {}
        for si, (_d, seg_nodes) in enumerate(segs):
            for k in seg_io[si][0]:
                consumers.setdefault(k, []).append(si)
        for si, (_d, seg_nodes) in enumerate(segs):
            outs = []
            for n in seg_nodes:
                nouts = len(n.op.list_outputs(n.attrs))
                for i in range(nouts):
                    k = (id(n), i)
                    if k in consumers or k in out_entries:
                        outs.append(k)
            seg_io[si][1] = outs
        self._segments = segs
        self._seg_io = seg_io
        self._seg_ni = ni_of
        self._seg_dev_of = dev_of

    def _seg_fn(self, si, is_train):
        key = ("seg", si, is_train,
               _ops_registry.trace_env_fingerprint())
        if key in self._fns:
            _telemetry.inc("xla.compile.fn_cache_hits")
            return self._fns[key]
        _dev, seg_nodes = self._segments[si]
        in_keys, out_keys = self._seg_io[si]
        ni_of = self._seg_ni
        # entry keys are ids — rebuild the local maps inside the closure
        def f(in_vals, rng):
            entry = dict(zip(in_keys, in_vals))
            aux_updates = []
            for node in seg_nodes:
                op = node.op
                na = node.num_args()
                ins = [entry[(id(c), ci)] for c, ci in node.inputs[:na]]
                auxs = [entry[(id(c), ci)] for c, ci in node.inputs[na:]]
                k = jax.random.fold_in(rng, ni_of[id(node)]) \
                    if op.needs_rng else None
                outs, aux_up = op.apply(node.attrs, ins, auxs, is_train, k)
                for i, o in enumerate(outs):
                    entry[(id(node), i)] = o
                if aux_up is not None and is_train:
                    for (child, _ci), new in zip(node.inputs[na:], aux_up):
                        aux_updates.append((child.name, new))
            return [entry[k2] for k2 in out_keys], dict(aux_updates)

        attrib = (self._symbol_name(), "seg%d" % si) \
            if _perfdebug.enabled() or _compile_cache.recording() else None
        fn = _DeviceHintFn(jax.jit(f), _dev.device_type,
                           self._note_build(key), attrib,
                           kind=("seg", si, is_train))
        self._fns[key] = fn
        return fn

    def _forward_segmented(self, is_train):
        """Forward across placement segments; training stores a vjp chain
        for ``backward``."""
        entry = {}
        arg_map = {n: a for n, a in self.arg_dict.items()}
        for node in self._symbol._nodes():
            if not node.is_variable:
                continue
            arr = arg_map.get(node.name)
            if arr is None:
                arr = self.aux_dict.get(node.name)
            if arr is None:
                raise MXNetError("unbound variable %r" % node.name)
            entry[(id(node), 0)] = arr._jx
        rng = self.next_rng()
        diff = set(self._diff_names())
        chain = []
        new_aux_all = {}
        train_grads = is_train and bool(diff)
        for si, (dev, _seg_nodes) in enumerate(self._segments):
            in_keys, out_keys = self._seg_io[si]
            jdev = dev.jax_device()
            ins = [jax.device_put(entry[k], jdev) for k in in_keys]
            srng = jax.device_put(rng, jdev)
            fn = self._seg_fn(si, is_train)
            if train_grads:
                outs, vjp_fn, aux_d = jax.vjp(
                    lambda vals: fn(vals, srng), ins, has_aux=True)
            else:
                outs, aux_d = fn(ins, rng=srng)
                vjp_fn = None
            for k, v in zip(out_keys, outs):
                entry[k] = v
            new_aux_all.update(aux_d)
            chain.append((vjp_fn, in_keys, out_keys,
                          [(o.shape, o.dtype) for o in outs], dev))
        if is_train:
            for name, v in new_aux_all.items():
                arr = self.aux_dict.get(name)
                if arr is not None:
                    arr._jx = v
        outs = [entry[(id(n), i)] for n, i in self._symbol._outputs]
        self._seg_chain = chain if train_grads else None
        self._pending_grads = "segmented" if train_grads else None
        self._last_state = None
        out_ctx = self._segments[-1][0]
        self.outputs = [NDArray._from_jax(o, out_ctx) for o in outs]
        if self._monitor_callback is not None:
            for name, arr in zip(self.output_names, self.outputs):
                self._monitor_callback(name, arr)
        return self.outputs

    def _backward_segmented(self, out_grads):
        """Chain segment vjps in reverse; cross-segment cotangents hop
        devices exactly where ``_CrossDeviceCopy`` nodes would sit."""
        cot = {}
        out_entries = [(id(n), i) for n, i in self._symbol._outputs]
        if out_grads is None:
            for k, o in zip(out_entries, self.outputs):
                cot[k] = jnp.ones(o.shape, o.dtype)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            for k, g in zip(out_entries, out_grads):
                cot[k] = g._jx if isinstance(g, NDArray) else jnp.asarray(g)
        var_name = {id(n): n.name for n in self._symbol._nodes()
                    if n.is_variable}
        diff = set(self._diff_names())
        grads = {}
        for vjp_fn, in_keys, out_keys, out_avals, dev in \
                reversed(self._seg_chain):
            jdev = dev.jax_device()
            out_cots = tuple(
                jax.device_put(cot[k], jdev) if k in cot
                else jnp.zeros(shape, dtype)
                for k, (shape, dtype) in zip(out_keys, out_avals))
            (in_cots,) = vjp_fn(list(out_cots))
            for k, c in zip(in_keys, in_cots):
                nm = var_name.get(k[0])
                if nm is not None:
                    if nm in diff:
                        grads[nm] = grads[nm] + c if nm in grads else c
                else:
                    cot[k] = cot[k] + c if k in cot else c
        return grads

    def _small_target(self):
        """Placement for executor-owned smalls (rng key, guard scalar):
        the executor's device — or, when the arrays are global over a
        single-process mesh, replicated over that mesh (a device-0
        committed scalar cannot enter a jit whose other arguments span
        the mesh)."""
        if self._spmd_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return NamedSharding(self._spmd_mesh, PartitionSpec())
        return self._ctx.jax_device()

    # -- in-graph NaN guard ----------------------------------------------
    def _nan_acc_in(self):
        """The accumulator value to feed the next guarded dispatch."""
        if self._nan_acc is not None:
            return self._nan_acc
        if self._nan_false is None:
            self._nan_false = jax.device_put(np.zeros((), np.bool_),
                                             self._small_target())
        return self._nan_false

    def consume_nan_flag(self):
        """Read-and-reset the accumulated in-graph guard flag: ONE scalar
        device→host transfer (blocks until the steps that produced it
        complete — the caller picks the cadence via
        ``MXNET_NAN_CHECK_PERIOD``)."""
        if self._nan_acc is None:
            return False
        flag = bool(np.asarray(self._nan_acc))  # host-sync: ok — one scalar at the guard cadence
        self._nan_acc = None
        self._nan_stale = False
        return flag

    def next_rng(self):
        """Per-dispatch rng key on the executor's device.

        Graphs with no rng-consuming ops (the common CNN case) reuse ONE
        cached device key — XLA dead-code-eliminates the argument, and the
        per-step ``jax.random.split`` dispatch + ``device_put`` round trip
        (tens of ms through a tunneled chip) disappear from the hot loop.
        Graphs that do consume rng draw a fresh key every dispatch."""
        if self._needs_rng is None:
            self._needs_rng = any(
                (not n.is_variable) and n.op.needs_rng
                for n in self._symbol._nodes())
        if self._global_mesh is not None:
            # multi-process SPMD: the key must be a global replicated
            # array (and identical on every process — fold a counter on a
            # fixed base rather than splitting process-local state).  The
            # counter advances HERE so every caller (forward, fused step,
            # bulk) gets a fresh key.
            from . import dist as _dist

            if self._needs_rng:
                self._rng_step += 1
                key = np.asarray(jax.random.fold_in(  # host-sync: ok — tiny key, dist replication needs host numpy
                    jax.random.PRNGKey(_random.get_seed()), self._rng_step))
                return _dist.replicate(self._global_mesh, key)
            if self._rng_cache is None:
                self._rng_cache = _dist.replicate(
                    self._global_mesh,
                    np.asarray(jax.random.PRNGKey(0)))  # host-sync: ok — one-time key replication
            return self._rng_cache
        if self._needs_rng:
            return jax.device_put(_random.next_key(),
                                  self._small_target())
        if self._rng_cache is None:
            self._rng_cache = jax.device_put(_random.next_key(),
                                             self._small_target())
        return self._rng_cache

    # -- compile-once warm-up (docs/how_to/perf.md "Compile once") --------
    def precompile(self, entries, logger=logging):
        """AOT-build the programs a warm-up manifest recorded: for each
        entry, rebuild the jitted function for its kind, ``lower`` it
        against the recorded abstract signature and ``compile`` — with
        the persistent compile cache populated this is a disk load, not
        an XLA compile, so a restart performs zero cold compiles before
        its first real dispatch.  Nothing is EXECUTED: no parameter,
        optimizer or rng state is touched, which is what makes this safe
        immediately before an exact ``resume="auto"`` restart.

        A program whose lowered HLO no longer matches the manifest's
        fingerprint is the invalidation signal (counted + logged — the
        fresh build simply wins); entries that cannot be reconstructed
        (placement segments, foreign kinds, shape mismatches) are
        skipped or counted as errors, never raised.  Returns a summary
        dict."""
        out = {"replayed": 0, "skipped": 0, "errors": 0,
               "fingerprint_changes": 0}
        for e in entries:
            try:
                kind = _compile_cache.kind_from_json(e.get("kind"))
            except MXNetError:
                out["skipped"] += 1
                continue
            head = kind if isinstance(kind, str) \
                else (kind[0] if kind else None)
            sig = e.get("sig")
            if head not in _compile_cache.REPLAYABLE_KINDS or sig is None:
                out["skipped"] += 1
                continue
            try:
                args, kwargs = _compile_cache.signature_from_json(
                    sig, device=self._ctx.jax_device())
                fn = self._get_fn(kind)
                lowered = fn.lower(*args, **kwargs)
                if e.get("fingerprint"):
                    fp = _perfdebug.fingerprint_text(lowered.as_text())
                    if fp != e["fingerprint"]:
                        out["fingerprint_changes"] += 1
                        _telemetry.inc(
                            "compile_cache.manifest.fingerprint_changes")
                        _telemetry.event(
                            "compile_cache.fingerprint_change",
                            exec=e.get("exec"), kind=e.get("kind_name"),
                            shapes=e.get("shapes"),
                            old=e["fingerprint"], new=fp)
                        logger.warning(
                            "compile_cache: %s/%s@%s lowers to different "
                            "HLO than the warm-up manifest recorded "
                            "(%s -> %s): code or trace-env changed since "
                            "the manifest was written; compiling fresh",
                            e.get("exec"), e.get("kind_name"),
                            e.get("shapes"), e["fingerprint"], fp)
                lowered.compile()
                out["replayed"] += 1
            except Exception as exn:  # noqa: broad-except — replay is
                # an optimization; a stale manifest entry must degrade
                # to lazy compilation, never break bind/fit/serving
                out["errors"] += 1
                _telemetry.inc("compile_cache.manifest.replay_errors")
                logger.warning(
                    "compile_cache: manifest replay of %s/%s@%s failed "
                    "(%s: %s); it will compile lazily instead",
                    e.get("exec"), e.get("kind_name"), e.get("shapes"),
                    type(exn).__name__, exn)
        return out

    # -- API --------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """reference ``executor.py:86``"""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("forward: unknown input %r" % k)
            dst = self.arg_dict[k]
            if isinstance(v, NDArray):
                src = v._transfer_src()
                val = src.astype(dst._jx.dtype) \
                    if src.dtype != dst._jx.dtype else src
                # inputs may live on another device (reference CopyFromTo
                # semantics): move to the executor's device; same-device
                # put is free
                dst._jx = jax.device_put(val, self._ctx.jax_device())
            else:
                dst[:] = v
        # per-dispatch batch flag: only a guarded TRAIN dispatch sets it —
        # an eval forward (score during a guarded fit) must never inherit
        # the last training batch's flag as a metric gate
        self._nan_batch = None
        if self._segments is not None:
            self._rng_step += 1
            return self._forward_segmented(is_train)
        args = [a._jx for a in self.arg_arrays]
        aux = [a._jx for a in self.aux_arrays]
        # rng must live on the executor's device: jit rejects mixed-device
        # args (e.g. cpu-bound module on a machine whose default is TPU)
        rng = self.next_rng()
        self._rng_step += 1
        fused_bwd = is_train and bool(self._diff_names())
        name = ("%s_forward%s" % (self._symbol_name(),
                                  "_backward" if fused_bwd else "")) \
            if _profiler.running() else ""
        with _profiler.span(name, "symbolic") as sp:
            if is_train:
                if self._diff_names():
                    if self._nan_guard:
                        outs, new_aux, grads, acc, batch_flag = \
                            self._get_fn("train_guard")(
                                args, aux, rng, self._nan_acc_in())
                        self._nan_acc = acc
                        self._nan_batch = batch_flag
                        self._nan_stale = False
                    else:
                        outs, new_aux, grads = self._get_fn("train")(
                            args, aux, rng)
                    self._pending_grads = grads
                    self._last_state = (args, aux, rng)
                    sp.sync(grads)
                else:
                    outs, new_aux = self._get_fn("train_fwd")(args, aux, rng)
                    self._pending_grads = None
                    self._last_state = None
                for arr, new in zip(self.aux_arrays, new_aux):
                    arr._jx = new
            else:
                outs = self._get_fn("predict")(args, aux, rng)
                self._pending_grads = None
                self._last_state = None
            sp.sync(outs)
        self.outputs = [NDArray._from_jax(o, self._ctx) for o in outs]
        if self._monitor_callback is not None:
            for name, arr in zip(self.output_names, self.outputs):
                self._monitor_callback(name, arr)
        return self.outputs

    def backward(self, out_grads=None):
        """reference ``executor.py:134`` — applies grads into grad arrays
        honoring grad_req (they were computed fused with forward)."""
        if not self._diff_names():
            return
        if self._pending_grads is None:
            raise MXNetError("backward called before forward(is_train=True)")
        if self._pending_grads == "segmented":
            grads = self._backward_segmented(out_grads)
        elif out_grads is None:
            grads = self._pending_grads
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            dev = self._ctx.jax_device()
            out_grads = [jax.device_put(
                g._jx if isinstance(g, NDArray) else jnp.asarray(g), dev)
                for g in out_grads]
            args, aux, rng = self._last_state
            bname = ("%s_backward" % self._symbol_name()) \
                if _profiler.running() else ""
            with _profiler.span(bname, "symbolic") as sp:
                _outs, _new_aux, grads = self._get_fn("train_with_grads")(
                    args, aux, rng, out_grads)
                sp.sync(grads)
        for name in self._diff_names():
            g = grads.get(name)
            dst = self.grad_dict.get(name)
            if dst is None:
                continue
            if g is None:
                # segmented (group2ctx) backward only produces cotangents
                # for variables reached by the chain; a bound-but-unused
                # differentiable param gets a zero gradient (write) or is
                # left untouched (add)
                if self.grad_req[name] != "add":
                    dst._jx = jnp.zeros_like(dst._jx)
                continue
            if self.grad_req[name] == "add":
                dst._jx = dst._jx + g
            else:
                dst._jx = g

    def set_monitor_callback(self, callback):
        """reference MXExecutorSetMonitorCallback (outputs-level monitor)."""
        self._monitor_callback = callback

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """reference ``executor.py`` copy_params_from"""
        for k, v in arg_params.items():
            if k in self.arg_dict:
                v.copyto(self.arg_dict[k])
            elif not allow_extra_params:
                raise MXNetError("unknown arg %r" % k)
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    v.copyto(self.aux_dict[k])
                elif not allow_extra_params:
                    raise MXNetError("unknown aux %r" % k)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new shapes; params with unchanged shapes are shared
        (reference executor.py reshape → shared-pool rebind; here the jit
        cache keys on shape so each shape compiles once)."""
        new_shapes = dict(kwargs)
        var_shape, var_dtype, _ = self._symbol._infer_shapes_full(new_shapes)
        arg_dict, grad_dict = {}, {}
        for n in self.arg_names:
            s = var_shape[n]
            if s == self.arg_dict[n].shape:
                arg_dict[n] = self.arg_dict[n]
                if self.grad_dict.get(n) is not None:
                    grad_dict[n] = self.grad_dict[n]
            else:
                if not (partial_shaping or n in kwargs or allow_up_sizing):
                    raise MXNetError(
                        "reshape: arg %r changes shape %s->%s without "
                        "partial_shaping" % (n, self.arg_dict[n].shape, s))
                arg_dict[n] = nd_zeros(s, ctx=self._ctx,
                                       dtype=self.arg_dict[n].dtype)
                if self.grad_req[n] != "null":
                    grad_dict[n] = nd_zeros(s, ctx=self._ctx,
                                            dtype=self.arg_dict[n].dtype)
        aux_dict = {}
        for n in self.aux_names:
            s = var_shape[n]
            aux_dict[n] = self.aux_dict[n] if s == self.aux_dict[n].shape \
                else nd_zeros(s, ctx=self._ctx, dtype=self.aux_dict[n].dtype)
        return Executor(self._symbol, self._ctx, arg_dict, grad_dict,
                        dict(self.grad_req), aux_dict, self._group2ctx)

    def debug_str(self):
        lines = ["Symbol outputs: %s" % self.output_names]
        for node in self._symbol._nodes():
            lines.append("%s %s" % (node.op.name if node.op else "var",
                                    node.name))
        return "\n".join(lines)

    # -- binding constructors --------------------------------------------
    @staticmethod
    def _bind(symbol, ctx, args, args_grad=None, grad_req="write",
              aux_states=None, group2ctx=None, shared_exec=None):
        """reference ``Executor::Bind`` ``graph_executor.cc:917``"""
        if isinstance(ctx, (list, tuple)):
            if len(ctx) != 1:
                raise MXNetError("Executor binds one context; use Module "
                                 "for multi-device data parallelism")
            ctx = ctx[0]
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            arg_dict = dict(zip(arg_names, args))
        else:
            arg_dict = dict(args)
        if aux_states is None:
            aux_dict = {}
        elif isinstance(aux_states, (list, tuple)):
            aux_dict = dict(zip(aux_names, aux_states))
        else:
            aux_dict = dict(aux_states)
        missing_aux = [n for n in aux_names if n not in aux_dict]
        if missing_aux:
            # allocate zero-init aux (shapes inferred from bound args)
            shapes = {n: a.shape for n, a in arg_dict.items()}
            var_shape, _vd, _ = symbol._infer_shapes_full(shapes)
            for n in missing_aux:
                aux_dict[n] = nd_zeros(var_shape[n], ctx=ctx)
        if args_grad is None:
            grad_dict = {}
        elif isinstance(args_grad, (list, tuple)):
            grad_dict = {n: g for n, g in zip(arg_names, args_grad)
                         if g is not None}
        else:
            grad_dict = dict(args_grad)
        return Executor(symbol, ctx, arg_dict, grad_dict, grad_req, aux_dict,
                        group2ctx)

    @staticmethod
    def _simple_bind(symbol, ctx, grad_req="write", type_dict=None,
                     shared_exec=None, group2ctx=None, **kwargs):
        """reference ``symbol.py:837`` simple_bind — infer + allocate."""
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        type_dict = dict(type_dict or {})
        # __shape__ attrs are consumed inside _infer_shapes_full
        for node in symbol._nodes():
            if node.is_variable and "__dtype__" in node.misc_attr \
                    and node.name not in type_dict:
                type_dict[node.name] = node.misc_attr["__dtype__"]
        var_shape, var_dtype, _ = symbol._infer_shapes_full(kwargs, type_dict)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        unknown = [n for n in arg_names + aux_names
                   if var_shape.get(n) is None]
        if unknown:
            raise MXNetError("simple_bind: cannot infer shapes for %s — "
                             "provide them as kwargs" % unknown)
        arg_dict = {}
        grad_dict = {}
        if isinstance(grad_req, str):
            req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            req = dict(zip(arg_names, grad_req))
        else:
            req = {n: grad_req.get(n, "null") for n in arg_names}
        for n in arg_names:
            dt = type_dict.get(n) or var_dtype.get(n) or np.float32
            arg_dict[n] = nd_zeros(var_shape[n], ctx=ctx, dtype=dt)
            if req.get(n, "null") != "null":
                grad_dict[n] = nd_zeros(var_shape[n], ctx=ctx, dtype=dt)
        aux_dict = {n: nd_zeros(var_shape[n], ctx=ctx,
                                dtype=var_dtype.get(n) or np.float32)
                    for n in aux_names}
        # shared_exec (bucketing): share parameter arrays with the shared
        # executor (reference shared data_pool_, graph_executor.cc:336-340)
        if shared_exec is not None:
            for n in arg_names:
                src = shared_exec.arg_dict.get(n)
                if src is not None and src.shape == arg_dict[n].shape:
                    arg_dict[n] = src
                    if n in shared_exec.grad_dict and n in grad_dict:
                        grad_dict[n] = shared_exec.grad_dict[n]
            for n in aux_names:
                src = shared_exec.aux_dict.get(n)
                if src is not None and src.shape == aux_dict[n].shape:
                    aux_dict[n] = src
        return Executor(symbol, ctx, arg_dict, grad_dict, req, aux_dict,
                        group2ctx)
