"""Global random state (``mx.random``).

Reference: ``python/mxnet/random.py`` + ``MXRandomSeed`` (seed is global,
per-device generators live in the resource manager, ``src/resource.cc:66``).
JAX PRNG is explicit-key, so the framework keeps one global key and splits
off a subkey per imperative sampling call; symbolic executors fold a per-call
key in as a hidden input (see ``executor.py``).  ``mx.random.seed(n)`` makes
everything reproducible exactly like the reference's global seed.
"""

from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "get_state", "set_state", "uniform",
           "normal", "randint"]

_lock = threading.Lock()
# lazy: building a PRNGKey runs a jit computation, which would initialize
# the jax backend (and the TPU tunnel) at package-import time — breaking
# host-only processes (PS server) and any later platform pinning
_key = None


_seed_value = 0


def seed(seed_state):
    """reference ``random.py:40`` / MXRandomSeed.

    Also seeds numpy's global RNG: the reference's initializers draw from
    the engine RNG that MXRandomSeed controls, so ``mx.random.seed(n)``
    makes ``init_params`` reproducible there — here the initializer zoo
    samples via ``np.random``, and seeding it keeps that contract."""
    import numpy as _np

    global _key, _seed_value
    with _lock:
        _seed_value = int(seed_state)
        _key = jax.random.PRNGKey(int(seed_state))
        _np.random.seed(int(seed_state) & 0xFFFFFFFF)


def get_seed():
    """The last value passed to ``seed()`` (0 before any call) — the
    shared base for multi-process SPMD keys, which must be identical on
    every process."""
    return _seed_value


def next_key():
    """Split off a fresh subkey from the global state."""
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.PRNGKey(0)
        _key, sub = jax.random.split(_key)
    return sub


def get_state():
    """JSON-able capture of the global RNG state — the jax key, the seed
    base, and numpy's generator — for exact mid-epoch training resume
    (docs/resilience.md): a resumed run draws the same sample stream an
    uninterrupted run would have."""
    import numpy as _np

    with _lock:
        key = None if _key is None \
            else _np.asarray(_key).astype(_np.uint32).tolist()
        seed_value = _seed_value
    kind, keys, pos, has_gauss, cached = _np.random.get_state()
    return {"seed": seed_value, "key": key,
            "np_state": {"kind": kind, "keys": keys.tolist(), "pos": pos,
                         "has_gauss": has_gauss, "cached": cached}}


def set_state(state):
    """Inverse of :func:`get_state`."""
    import numpy as _np

    global _key, _seed_value
    with _lock:
        _seed_value = int(state.get("seed", 0))
        key = state.get("key")
        _key = None if key is None \
            else jax.numpy.asarray(_np.asarray(key, _np.uint32))
    nps = state.get("np_state")
    if nps:
        _np.random.set_state((nps["kind"],
                              _np.asarray(nps["keys"], _np.uint32),
                              int(nps["pos"]), int(nps["has_gauss"]),
                              float(nps["cached"])))


def _nd():
    """ndarray imports this module at its top, so a top-level back-import
    would cycle; a sys.modules lookup also avoids the package import lock
    — kvstore-server handler threads run while ``import mxnet_tpu`` is
    still blocked in the auto server loop, and a ``from . import`` there
    deadlocks (see kvstore_server._pkg_mod)."""
    import sys as _sys

    mod = _sys.modules.get(__package__ + ".ndarray")
    if mod is None:  # pragma: no cover - only during partial init
        from . import ndarray as mod
    return mod


def uniform(low=0, high=1, shape=None, ctx=None, dtype="float32", out=None):
    return _nd().uniform(low=low, high=high,
                         shape=(1,) if shape is None else shape,
                         dtype=dtype, ctx=ctx, out=out)


def normal(loc=0, scale=1, shape=None, ctx=None, dtype="float32", out=None):
    return _nd().normal(loc=loc, scale=scale,
                        shape=(1,) if shape is None else shape,
                        dtype=dtype, ctx=ctx, out=out)


def randint(low, high, shape=(1,), ctx=None, dtype="int32"):
    import numpy as np

    k = next_key()
    arr = jax.random.randint(k, shape, low, high, dtype=np.dtype(dtype))
    return _nd().NDArray._from_jax(arr, ctx)
