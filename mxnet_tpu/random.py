"""Global random state (``mx.random``).

Reference: ``python/mxnet/random.py`` + ``MXRandomSeed`` (seed is global,
per-device generators live in the resource manager, ``src/resource.cc:66``).
JAX PRNG is explicit-key, so the framework keeps one global key and splits
off a subkey per imperative sampling call; symbolic executors fold a per-call
key in as a hidden input (see ``executor.py``).  ``mx.random.seed(n)`` makes
everything reproducible exactly like the reference's global seed.
"""

from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "uniform", "normal", "randint"]

_lock = threading.Lock()
_key = jax.random.PRNGKey(0)


def seed(seed_state):
    """reference ``random.py:40`` / MXRandomSeed"""
    global _key
    with _lock:
        _key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Split off a fresh subkey from the global state."""
    global _key
    with _lock:
        _key, sub = jax.random.split(_key)
    return sub


def uniform(low=0, high=1, shape=None, ctx=None, dtype="float32", out=None):
    from . import ndarray as nd

    return nd.uniform(low=low, high=high,
                      shape=(1,) if shape is None else shape,
                      dtype=dtype, ctx=ctx, out=out)


def normal(loc=0, scale=1, shape=None, ctx=None, dtype="float32", out=None):
    from . import ndarray as nd

    return nd.normal(loc=loc, scale=scale,
                     shape=(1,) if shape is None else shape,
                     dtype=dtype, ctx=ctx, out=out)


def randint(low, high, shape=(1,), ctx=None, dtype="int32"):
    from . import ndarray as nd
    import numpy as np

    k = next_key()
    arr = jax.random.randint(k, shape, low, high, dtype=np.dtype(dtype))
    return nd.NDArray._from_jax(arr, ctx)
