"""Telemetry — the process-wide metrics registry + structured event log.

The reference stack's observability is three disconnected point tools
(the chrome-trace profiler, the per-tensor ``Monitor``, the
``Speedometer`` log line); the TensorFlow system paper instead treats
run-level metrics and tracing as a first-class subsystem.  This module
is that subsystem for the TPU framework: every layer (``Module.fit``
phase timing, KVStore transport, XLA compile tracking, resilience
events, device memory) reports into ONE thread-safe registry, exposed as

* ``snapshot()``   — nested dict (counters / gauges / histograms / events)
* ``dump(path)``   — the snapshot as JSON
* ``dump_events(path)`` — the structured event log as JSONL
* ``prometheus_text()`` / ``write_prometheus(path)`` — Prometheus
  text-exposition format (``mxnet_``-prefixed metric names)

Cost model (the ``profiler.span.__init__`` trick): telemetry is OFF by
default and every recording call checks one module-level boolean first,
so a disabled counter bump is a single early-returning function call and
a disabled :class:`phase` timer does no clock reads — instrumentation
stays compiled into production hot paths at effectively zero cost
(tests/test_telemetry.py pins the per-batch overhead).

Enable with ``MXNET_TELEMETRY=1`` (or :func:`enable`).  Setting
``MXNET_TELEMETRY_DUMP=path`` implies enablement and atexit-writes the
snapshot JSON to ``path`` plus the event log to
``<path-sans-ext>.events.jsonl``.

Metric names are dotted families (``fit.*``, ``kvstore.*``, ``xla.*``,
``resilience.*``, ``elastic.*``, ``memory.*``, ``serving.*`` —
including the paged-KV occupancy gauges under ``serving.kv.*``); labels
are free-form keyword arguments (``inc("kvstore.push.count",
server=0)``).
"""

from __future__ import annotations

import bisect
import json
import os
import re
import threading
import time
from collections import deque

from . import profiler as _profiler

__all__ = ["enabled", "enable", "disable", "inc", "declare", "set_gauge",
           "observe", "event", "phase", "snapshot", "dump", "dump_events",
           "prometheus_text", "write_prometheus", "reset", "sample_memory",
           "phase_totals", "counter_total", "gauge_value", "hist_quantile",
           "hist_state", "quantile_from_counts", "events_recent",
           "add_phase_hook", "remove_phase_hook", "set_phase_hook",
           "aggregate", "start_exporter", "stop_exporter",
           "exporter_running"]

#: default histogram bucket upper bounds (seconds-flavored; callers may
#: pass their own on first ``observe`` of a metric)
DEFAULT_BUCKETS = (1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0, 60.0)

_lock = threading.Lock()
_counters = {}   # (name, labels) -> float
_gauges = {}     # (name, labels) -> float
_hists = {}      # (name, labels) -> _Histogram
_events = deque(maxlen=int(os.environ.get("MXNET_TELEMETRY_EVENTS_MAX",
                                          "10000")))

_enabled = (os.environ.get("MXNET_TELEMETRY", "0")
            not in ("0", "", "false")
            or bool(os.environ.get("MXNET_TELEMETRY_DUMP"))
            # an armed flight recorder (perfdebug) implies telemetry:
            # its dumps are built from the event ring and phase timings,
            # so a recorder without telemetry would dump hollow files
            # exactly when the post-mortem needs them
            or os.environ.get("MXNET_FLIGHT_RECORDER", "")
            not in ("0", "", "false")
            or bool(os.environ.get("MXNET_FLIGHT_RECORDER_DIR"))
            # an armed hang watchdog (sentinel) implies telemetry the
            # same way: its whole progress feed is the phase hook, and
            # phase exits only reach hooks while telemetry records — a
            # watchdog without telemetry would see a healthy job as
            # eternally stalled and false-trip at the deadline floor
            or os.environ.get("MXNET_WATCHDOG", "")
            not in ("0", "", "false")
            # an armed fleet exporter implies telemetry: its whole
            # output is this registry's snapshot, so an export dir over
            # a disabled registry would publish empty files forever
            or bool(os.environ.get("MXNET_TELEMETRY_EXPORT_DIR")))


def enabled():
    """True when the registry records (``MXNET_TELEMETRY=1`` or
    :func:`enable`); the one check every hot path makes."""
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def _key(name, labels):
    if not labels:
        return (name, ())
    return (name, tuple(sorted((k, str(v)) for k, v in labels.items())))


#: counter keys declared at zero (``inc(name, 0)``) — remembered across
#: :func:`reset` so an enabled-mode reset (the exporter keeps running,
#: a test clears mid-run) re-seeds the declared families instead of
#: silently dropping them from ``snapshot()``/Prometheus until their
#: next increment
_declared = set()


# -- recording --------------------------------------------------------------
def inc(name, value=1, **labels):
    """Add ``value`` to counter ``name`` (``inc(name, 0)`` declares it at
    zero so a family is visible in ``snapshot()`` before its first
    increment)."""
    if not _enabled:
        return
    k = _key(name, labels)
    with _lock:
        if value == 0:
            _declared.add(k)
        _counters[k] = _counters.get(k, 0) + value


def declare(*names):
    """Declare counter families at zero so they are visible in
    ``snapshot()``/Prometheus before their first increment (``fit``
    does this for the resilience family; ``compile_cache`` for the
    persistent-cache family)."""
    for name in names:
        inc(name, 0)


def set_gauge(name, value, **labels):
    """Set gauge ``name`` to ``value`` (last write wins)."""
    if not _enabled:
        return
    with _lock:
        _gauges[_key(name, labels)] = value


class _Histogram:
    __slots__ = ("buckets", "counts", "count", "sum", "min", "max")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1: overflow (+Inf)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v):
        v = float(v)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v


def observe(name, value, buckets=None, **labels):
    """Record ``value`` into histogram ``name`` (bucket bounds fixed by
    the first observation)."""
    if not _enabled:
        return
    k = _key(name, labels)
    with _lock:
        h = _hists.get(k)
        if h is None:
            h = _hists[k] = _Histogram(buckets or DEFAULT_BUCKETS)
        h.observe(value)


def event(name, **fields):
    """Append one structured event (``{"ts", "event", **fields}``) to the
    in-memory JSONL log (bounded ring; ``dump_events`` exports)."""
    if not _enabled:
        return
    rec = {"ts": round(time.time(), 6), "event": name}
    rec.update(fields)
    with _lock:
        _events.append(rec)


def events_recent(n=100):
    """The newest ``n`` structured events (copies) — what the flight
    recorder folds into a crash dump."""
    with _lock:
        return [dict(r) for r in list(_events)[-int(n):]]


#: registered per-phase observers, each called as ``hook(family,
#: phase_name, seconds)`` from an ENABLED phase's exit.  Two consumers
#: exist today — the flight recorder's per-batch timing feed
#: (:mod:`mxnet_tpu.perfdebug`) and the training watchdog's progress
#: feed (:mod:`mxnet_tpu.sentinel`) — which is exactly why this is a
#: LIST: the old single ``set_phase_hook`` slot meant whoever installed
#: second silently evicted the other.  Stored as a tuple so the hot
#: path iterates a stable snapshot (one truthiness check when empty);
#: registration swaps the whole tuple under ``_lock``.
_phase_hooks = ()
#: the hook installed through the deprecated ``set_phase_hook`` alias
#: (so a second ``set_phase_hook`` call keeps its replace semantics
#: without evicting ``add_phase_hook`` registrations)
_set_alias_hook = None


def add_phase_hook(hook):
    """Register a phase observer (``hook(family, phase, seconds)``);
    duplicate registrations are ignored.  Returns ``hook`` so callers
    can hold it for :func:`remove_phase_hook`."""
    global _phase_hooks
    with _lock:
        if hook not in _phase_hooks:
            _phase_hooks = _phase_hooks + (hook,)
    return hook


def remove_phase_hook(hook):
    """Unregister a phase observer; unknown hooks are a no-op."""
    global _phase_hooks
    with _lock:
        _phase_hooks = tuple(h for h in _phase_hooks if h is not hook)


def set_phase_hook(hook):
    """Deprecated single-slot spelling: replaces only the hook a
    previous ``set_phase_hook`` installed (or clears it with ``None``)
    — registrations made through :func:`add_phase_hook` are never
    evicted.  New code should use ``add_phase_hook`` /
    ``remove_phase_hook``."""
    global _phase_hooks, _set_alias_hook
    with _lock:
        hooks = tuple(h for h in _phase_hooks if h is not _set_alias_hook)
        _set_alias_hook = hook
        if hook is not None:
            hooks = hooks + (hook,)
        _phase_hooks = hooks


class phase:
    """Time one training-loop phase: a histogram observation in
    ``<family>.phase_seconds{phase=<name>}`` and — when the profiler is
    running — a chrome-trace span via ``profiler.record``.

    Disabled-cheap like ``profiler.span``: the enabled check happens once
    in ``__init__`` and a disabled phase does no clock reads.  Note JAX
    dispatch is asynchronous, so device compute time is attributed to the
    first phase that blocks on results (see docs/observability.md) — in
    the sync-free fit loop that is the explicit ``sync`` phase (device
    metric reads, NaN-guard flag reads), which exists precisely so
    ``metric`` and friends time only their dispatch work.
    """

    __slots__ = ("_name", "_family", "_t0", "_on", "_prof")

    def __init__(self, name, family="fit"):
        self._prof = _profiler.running()
        self._on = _enabled or self._prof
        if self._on:
            self._name = name
            self._family = family

    def __enter__(self):
        if self._on:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._on:
            dt = time.perf_counter() - self._t0
            if _enabled:
                observe(self._family + ".phase_seconds", dt,
                        phase=self._name)
            if self._prof:
                end = _profiler._now_us()
                _profiler.record("%s:%s" % (self._family, self._name),
                                 "phase", end - dt * 1e6, end)
            if _phase_hooks:
                for hook in _phase_hooks:
                    hook(self._family, self._name, dt)
        return False


# -- derived reads ----------------------------------------------------------
def phase_totals(family="fit"):
    """``{phase: (sum_seconds, count)}`` for one family's phase
    histograms — the per-phase step-time breakdown consumers
    (``TelemetryReport``, ``bench.py``) read."""
    name = family + ".phase_seconds"
    out = {}
    with _lock:
        for (n, labels), h in _hists.items():
            if n == name:
                out[dict(labels).get("phase", "")] = (h.sum, h.count)
    return out


def counter_total(name):
    """Sum of counter ``name`` across all label sets (0 when absent)."""
    with _lock:
        return sum(v for (n, _), v in _counters.items() if n == name)


def gauge_value(name, **labels):
    """Current value of gauge ``name`` (None when unset)."""
    with _lock:
        return _gauges.get(_key(name, labels))


def hist_quantile(name, q, **labels):
    """Estimate the ``q``-quantile (0..1) of histogram ``name`` from its
    bucket counts — linear interpolation inside the target bucket, the
    observed min/max capping the first/overflow buckets.  What the
    serving layer's p50/p99 reads (and Prometheus' ``histogram_quantile``
    would compute from the same exposition); None when unobserved."""
    with _lock:
        h = _hists.get(_key(name, labels))
        if h is None or h.count == 0:
            return None
        target = q * h.count
        acc = 0
        lo = h.min
        for b, c in zip(h.buckets, h.counts):
            if acc + c >= target:
                if c == 0:
                    return min(lo, h.max)
                frac = (target - acc) / c
                return min(lo + (min(b, h.max) - lo) * max(0.0, frac),
                           h.max)
            acc += c
            lo = max(lo, b)
        return h.max  # overflow bucket: cap at the observed max


def hist_state(name, **labels):
    """Raw histogram state — bucket bounds, per-bucket counts (the last
    entry is the overflow bucket), total count/sum and observed min/max
    — or None when unobserved.  Windowed-quantile readers (the fleet
    controller's TTFT-p99 window) diff two snapshots' counts and feed
    the delta to :func:`quantile_from_counts`; cumulative
    :func:`hist_quantile` would smear the whole process history into
    the estimate."""
    with _lock:
        h = _hists.get(_key(name, labels))
        if h is None:
            return None
        return {"buckets": tuple(h.buckets), "counts": list(h.counts),
                "count": h.count, "sum": h.sum,
                "min": h.min, "max": h.max}


def quantile_from_counts(buckets, counts, q, lo=None, hi=None):
    """:func:`hist_quantile`'s estimator over caller-supplied bucket
    counts (e.g. the delta of two :func:`hist_state` reads).  ``lo`` /
    ``hi`` cap the first/overflow buckets the way the histogram's
    observed min/max do; they default to 0 and the last finite bound.
    None when the counts are empty."""
    total = sum(counts)
    if total <= 0:
        return None
    lo = 0.0 if lo is None else float(lo)
    hi = float(buckets[-1]) if hi is None else float(hi)
    target = q * total
    acc = 0
    cur = lo
    for b, c in zip(buckets, counts):
        if acc + c >= target:
            if c == 0:
                return min(cur, hi)
            frac = (target - acc) / c
            return min(cur + (min(b, hi) - cur) * max(0.0, frac), hi)
        acc += c
        cur = max(cur, b)
    return hi  # overflow bucket: cap at hi


# -- memory sampling --------------------------------------------------------
def sample_memory():
    """Sample device (HBM) memory stats from JAX into ``memory.device.*``
    gauges, plus the host max-RSS so the memory family exists even on
    backends (CPU) whose devices expose no ``memory_stats``."""
    if not _enabled:
        return
    try:
        import jax

        devices = jax.local_devices()
    except (ImportError, RuntimeError):
        devices = []
    for d in devices:
        stats_fn = getattr(d, "memory_stats", None)
        stats = None
        if stats_fn is not None:
            try:
                stats = stats_fn()
            except (RuntimeError, NotImplementedError):
                stats = None  # backend without allocator stats
        if not stats:
            continue
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in stats:
                set_gauge("memory.device.%s" % k, stats[k],
                          device=getattr(d, "id", 0))
    try:
        import resource
        import sys

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss unit is kilobytes on Linux but bytes on macOS
        if sys.platform != "darwin":
            rss *= 1024
        set_gauge("memory.host.max_rss_bytes", rss)
    except (ImportError, ValueError, OSError):  # non-POSIX host
        pass


# -- exporters --------------------------------------------------------------
def _label_str(labels):
    return ",".join("%s=%s" % kv for kv in labels)


def _hist_dict(h):
    cum, acc = {}, 0
    for b, c in zip(h.buckets, h.counts):
        acc += c
        cum["%g" % b] = acc
    cum["+Inf"] = acc + h.counts[-1]
    return {"count": h.count, "sum": h.sum, "min": h.min, "max": h.max,
            "mean": (h.sum / h.count) if h.count else 0.0, "buckets": cum}


def snapshot():
    """The whole registry as a nested dict:
    ``{enabled, counters: {name: {labels: v}}, gauges: {...},
    histograms: {name: {labels: {count,sum,min,max,mean,buckets}}},
    events: {count, recent}}``."""
    with _lock:
        counters, gauges, hists = {}, {}, {}
        for (n, labels), v in sorted(_counters.items()):
            counters.setdefault(n, {})[_label_str(labels)] = v
        for (n, labels), v in sorted(_gauges.items()):
            gauges.setdefault(n, {})[_label_str(labels)] = v
        for (n, labels), h in sorted(_hists.items()):
            hists.setdefault(n, {})[_label_str(labels)] = _hist_dict(h)
        return {"enabled": _enabled, "counters": counters, "gauges": gauges,
                "histograms": hists,
                "events": {"count": len(_events),
                           "recent": list(_events)[-100:]}}


def dump(path):
    """Write ``snapshot()`` as JSON; returns ``path``."""
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=1, default=str)
    return path


def dump_events(path):
    """Write the structured event log as JSONL (one event per line);
    returns ``path``."""
    with _lock:
        events = list(_events)
    with open(path, "w") as f:
        for rec in events:
            f.write(json.dumps(rec, default=str))
            f.write("\n")
    return path


def _prom_name(name):
    s = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    return s if s.startswith("mxnet_") else "mxnet_" + s


def _prom_esc(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _prom_labels(labels, extra=()):
    items = list(labels) + list(extra)
    if not items:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _prom_esc(v))
                             for k, v in items)


def _prom_num(v):
    v = float(v)
    return "%d" % int(v) if v.is_integer() else repr(v)


def _parse_label_str(s):
    """Invert :func:`_label_str`: ``"a=1,b=x"`` -> ``[("a","1"),
    ("b","x")]`` (the snapshot's label encoding, shared by
    :func:`aggregate` and the Prometheus renderer)."""
    if not s:
        return []
    out = []
    for part in s.split(","):
        k, _, v = part.partition("=")
        out.append((k, v))
    return out


def _bucket_order(bound):
    return float("inf") if bound == "+Inf" else float(bound)


def prometheus_text(snap=None):
    """The registry — or any :func:`snapshot`/:func:`aggregate`-shaped
    dict passed as ``snap`` — in Prometheus text-exposition format
    (counter / gauge / histogram types, cumulative ``le`` buckets)."""
    if snap is None:
        snap = snapshot()
    lines = []
    for kind, store in (("counter", snap.get("counters", {})),
                        ("gauge", snap.get("gauges", {}))):
        for name in sorted(store):
            pname = _prom_name(name)
            lines.append("# TYPE %s %s" % (pname, kind))
            for lstr in sorted(store[name]):
                lines.append("%s%s %s" % (
                    pname, _prom_labels(_parse_label_str(lstr)),
                    _prom_num(store[name][lstr])))
    for name in sorted(snap.get("histograms", {})):
        pname = _prom_name(name)
        lines.append("# TYPE %s histogram" % pname)
        for lstr in sorted(snap["histograms"][name]):
            h = snap["histograms"][name][lstr]
            labels = _parse_label_str(lstr)
            for b in sorted(h["buckets"], key=_bucket_order):
                lines.append("%s_bucket%s %d" % (
                    pname, _prom_labels(labels, [("le", b)]),
                    h["buckets"][b]))
            lines.append("%s_sum%s %s" % (pname, _prom_labels(labels),
                                          _prom_num(h["sum"])))
            lines.append("%s_count%s %d" % (pname, _prom_labels(labels),
                                            h["count"]))
    return "\n".join(lines) + "\n"


def write_prometheus(path):
    """Write :func:`prometheus_text` to ``path`` (e.g. for a node-exporter
    textfile collector); returns ``path``."""
    with open(path, "w") as f:
        f.write(prometheus_text())
    return path


# -- fleet aggregation -------------------------------------------------------
def _merge_hists(dicts):
    """Merge several :func:`_hist_dict`-shaped histograms bucket-wise:
    each cumulative bucket series is decomposed into per-bucket counts,
    summed over the union of bounds, and re-accumulated — so a fleet
    quantile comes from MERGED buckets, not an average of per-process
    quantiles."""
    bounds = sorted({_bucket_order(b) for d in dicts
                     for b in d.get("buckets", {}) if b != "+Inf"})
    idx = {b: i for i, b in enumerate(bounds)}
    per = [0] * (len(bounds) + 1)   # +1: overflow
    count, total = 0, 0.0
    mn = mx = None
    for d in dicts:
        cum = d.get("buckets", {})
        prev = 0
        for b in sorted((b for b in cum if b != "+Inf"),
                        key=_bucket_order):
            per[idx[_bucket_order(b)]] += cum[b] - prev
            prev = cum[b]
        per[-1] += cum.get("+Inf", prev) - prev
        count += d.get("count", 0)
        total += d.get("sum", 0.0)
        if d.get("min") is not None:
            mn = d["min"] if mn is None else min(mn, d["min"])
        if d.get("max") is not None:
            mx = d["max"] if mx is None else max(mx, d["max"])
    merged, acc = {}, 0
    for b, c in zip(bounds, per[:-1]):
        acc += c
        merged["%g" % b] = acc
    merged["+Inf"] = acc + per[-1]
    return {"count": count, "sum": total, "min": mn, "max": mx,
            "mean": (total / count) if count else 0.0, "buckets": merged}


def aggregate(directory=None, snapshots=None, include_local=False):
    """Merge several processes' registries into ONE snapshot-shaped
    dict (renderable by ``prometheus_text(snap)``):

    * **counters** are summed per (family, label set) — fleet totals;
    * **gauges** keep one entry per process, the label set extended
      with ``proc=<name>`` (a gauge is a state, not a flow: summing
      two replicas' ``slot_occupancy`` would fabricate a third state);
    * **histograms** merge bucket-wise (:func:`_merge_hists`) so fleet
      quantiles come from combined buckets;
    * **events** concatenate (each tagged with its ``proc``), newest
      last, bounded to the per-process ring size.

    Sources: every ``*.telemetry.json`` under ``directory`` (the
    :func:`start_exporter` layout; torn or garbled files are skipped —
    they lose one cadence, not the merge), plus any pre-loaded
    ``snapshots`` dicts, plus this process's live registry when
    ``include_local`` (tagged ``proc=local`` unless the exporter names
    it).  Returns ``{"procs": [...], "counters", "gauges",
    "histograms", "events"}``."""
    snaps = list(snapshots or ())
    local_proc = _exporter.proc if _exporter is not None else "local"
    if directory:
        try:
            names = sorted(os.listdir(directory))
        except OSError:
            names = []
        for fn in names:
            if not fn.endswith(".telemetry.json"):
                continue
            # include_local reads THIS process from its live registry;
            # its own (staler) export file must not double-count it
            if include_local \
                    and fn == "%s.telemetry.json" % local_proc:
                continue
            try:
                with open(os.path.join(directory, fn)) as f:
                    snaps.append(json.load(f))
            except (OSError, ValueError):
                continue
    if include_local:
        snaps.append(dict(snapshot(), proc=local_proc))
    procs, counters, gauges, hist_parts = [], {}, {}, {}
    events = []
    for i, s in enumerate(snaps):
        proc = str(s.get("proc") or "p%d" % i)
        procs.append(proc)
        for name, by_label in s.get("counters", {}).items():
            dst = counters.setdefault(name, {})
            for lstr, v in by_label.items():
                dst[lstr] = dst.get(lstr, 0) + v
        for name, by_label in s.get("gauges", {}).items():
            dst = gauges.setdefault(name, {})
            for lstr, v in by_label.items():
                dst[(lstr + "," if lstr else "") + "proc=" + proc] = v
        for name, by_label in s.get("histograms", {}).items():
            dst = hist_parts.setdefault(name, {})
            for lstr, h in by_label.items():
                dst.setdefault(lstr, []).append(h)
        recent = s.get("events", {}).get("recent", [])
        events.extend(dict(r, proc=proc) for r in recent)
    hists = {name: {lstr: _merge_hists(parts)
                    for lstr, parts in by_label.items()}
             for name, by_label in hist_parts.items()}
    events.sort(key=lambda r: r.get("ts", 0))
    events = events[-_events.maxlen:]
    return {"enabled": True, "procs": procs, "counters": counters,
            "gauges": gauges, "histograms": hists,
            "events": {"count": len(events), "recent": events}}


# -- fleet export ------------------------------------------------------------
class _Exporter(threading.Thread):
    """Cadence thread publishing this process's registry as an atomic
    snapshot file ``<proc>.telemetry.json`` under the export dir — the
    same one-file-per-member layout as ``tools/supervise.py``'s
    heartbeat dir, so a supervised fleet's telemetry and liveness live
    side by side."""

    def __init__(self, directory, interval, proc):
        super().__init__(name="telemetry-export", daemon=True)
        self.directory = directory
        self.interval = interval
        self.proc = proc
        self.path = os.path.join(directory, "%s.telemetry.json" % proc)
        self._stop_ev = threading.Event()

    def write_once(self):
        """One atomic snapshot publish; never raises (a full disk
        loses one cadence, not the process)."""
        from .base import atomic_write

        payload = dict(snapshot(), proc=self.proc, pid=os.getpid(),
                       export_ts=round(time.time(), 6))
        blob = json.dumps(payload, default=str)

        def _w(tmp):
            with open(tmp, "w") as f:
                f.write(blob)

        try:
            # durable=False: the cadence republishes in seconds; an
            # fsync stall on a loaded host must not back up the fleet
            atomic_write(self.path, _w, durable=False)
        except OSError:
            pass

    def run(self):
        while not self._stop_ev.wait(self.interval):
            self.write_once()
        self.write_once()   # final publish: exit totals are visible

    def stop(self, timeout=5.0):
        self._stop_ev.set()
        self.join(timeout)


_exporter = None


def start_exporter(directory=None, interval_s=None, proc=None):
    """Arm the fleet export thread (idempotent: a live exporter is
    returned as-is, so repeated arming — or a :func:`reset` — can
    never stack cadence threads).  Defaults come from
    ``MXNET_TELEMETRY_EXPORT_DIR`` / ``_INTERVAL_S`` / ``_PROC``;
    implies :func:`enable` and writes the first snapshot immediately
    (a just-launched worker is visible before its first cadence).
    Also registers a final atexit publish."""
    global _exporter
    if _exporter is not None and _exporter.is_alive():
        return _exporter
    directory = directory or os.environ.get("MXNET_TELEMETRY_EXPORT_DIR")
    if not directory:
        raise ValueError("start_exporter needs a directory (or "
                         "MXNET_TELEMETRY_EXPORT_DIR)")
    if interval_s is None:
        try:
            interval_s = float(os.environ.get(
                "MXNET_TELEMETRY_EXPORT_INTERVAL_S", "2.0") or 2.0)
        except ValueError:
            interval_s = 2.0
    proc = proc or os.environ.get("MXNET_TELEMETRY_EXPORT_PROC") \
        or "pid%d" % os.getpid()
    enable()
    os.makedirs(directory, exist_ok=True)
    _exporter = _Exporter(directory, max(0.05, float(interval_s)), proc)
    _exporter.write_once()
    _exporter.start()
    import atexit

    atexit.register(_atexit_export)
    return _exporter


def _atexit_export():  # pragma: no cover - exercised via subprocess test
    if _exporter is not None and _exporter.is_alive():
        _exporter.stop()


def stop_exporter():
    """Stop the export thread (final snapshot included); no-op when
    none is armed."""
    global _exporter
    exp, _exporter = _exporter, None
    if exp is not None and exp.is_alive():
        exp.stop()


def exporter_running():
    """True while the cadence thread is alive (the reset-audit test's
    leak probe)."""
    return _exporter is not None and _exporter.is_alive()


def reset():
    """Clear all metrics and events (tests; enablement is unchanged).

    While ENABLED, counter families declared at zero (``inc(name,
    0)``) are re-seeded rather than dropped — a mid-run reset under a
    live exporter must not make declared families vanish from the
    exposition until their next increment.  A disabled reset clears
    everything (the test fixtures' teardown path).  The export thread,
    if armed, is left running: it publishes whatever the registry
    holds and is stopped only by :func:`stop_exporter`."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _events.clear()
        if _enabled:
            for k in _declared:
                _counters[k] = 0


def _atexit_dump():  # pragma: no cover - exercised via subprocess test
    path = os.environ.get("MXNET_TELEMETRY_DUMP")
    if not path:
        return
    try:
        dump(path)
        dump_events(os.path.splitext(path)[0] + ".events.jsonl")
    except OSError as e:
        import logging

        logging.warning("telemetry: could not write %r at exit: %s",
                        path, e)


if os.environ.get("MXNET_TELEMETRY_DUMP"):
    import atexit

    atexit.register(_atexit_dump)

if os.environ.get("MXNET_TELEMETRY_EXPORT_DIR"):
    # env-armed fleet export: the process publishes itself from import
    # on, no call site needed (supervised children get the dir from
    # tools/supervise.py --telemetry-dir)
    start_exporter()
