"""Telemetry — the process-wide metrics registry + structured event log.

The reference stack's observability is three disconnected point tools
(the chrome-trace profiler, the per-tensor ``Monitor``, the
``Speedometer`` log line); the TensorFlow system paper instead treats
run-level metrics and tracing as a first-class subsystem.  This module
is that subsystem for the TPU framework: every layer (``Module.fit``
phase timing, KVStore transport, XLA compile tracking, resilience
events, device memory) reports into ONE thread-safe registry, exposed as

* ``snapshot()``   — nested dict (counters / gauges / histograms / events)
* ``dump(path)``   — the snapshot as JSON
* ``dump_events(path)`` — the structured event log as JSONL
* ``prometheus_text()`` / ``write_prometheus(path)`` — Prometheus
  text-exposition format (``mxnet_``-prefixed metric names)

Cost model (the ``profiler.span.__init__`` trick): telemetry is OFF by
default and every recording call checks one module-level boolean first,
so a disabled counter bump is a single early-returning function call and
a disabled :class:`phase` timer does no clock reads — instrumentation
stays compiled into production hot paths at effectively zero cost
(tests/test_telemetry.py pins the per-batch overhead).

Enable with ``MXNET_TELEMETRY=1`` (or :func:`enable`).  Setting
``MXNET_TELEMETRY_DUMP=path`` implies enablement and atexit-writes the
snapshot JSON to ``path`` plus the event log to
``<path-sans-ext>.events.jsonl``.

Metric names are dotted families (``fit.*``, ``kvstore.*``, ``xla.*``,
``resilience.*``, ``elastic.*``, ``memory.*``); labels are free-form
keyword arguments (``inc("kvstore.push.count", server=0)``).
"""

from __future__ import annotations

import bisect
import json
import os
import re
import threading
import time
from collections import deque

from . import profiler as _profiler

__all__ = ["enabled", "enable", "disable", "inc", "declare", "set_gauge",
           "observe", "event", "phase", "snapshot", "dump", "dump_events",
           "prometheus_text", "write_prometheus", "reset", "sample_memory",
           "phase_totals", "counter_total", "gauge_value", "hist_quantile",
           "hist_state", "quantile_from_counts", "events_recent",
           "add_phase_hook", "remove_phase_hook", "set_phase_hook"]

#: default histogram bucket upper bounds (seconds-flavored; callers may
#: pass their own on first ``observe`` of a metric)
DEFAULT_BUCKETS = (1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0, 60.0)

_lock = threading.Lock()
_counters = {}   # (name, labels) -> float
_gauges = {}     # (name, labels) -> float
_hists = {}      # (name, labels) -> _Histogram
_events = deque(maxlen=int(os.environ.get("MXNET_TELEMETRY_EVENTS_MAX",
                                          "10000")))

_enabled = (os.environ.get("MXNET_TELEMETRY", "0")
            not in ("0", "", "false")
            or bool(os.environ.get("MXNET_TELEMETRY_DUMP"))
            # an armed flight recorder (perfdebug) implies telemetry:
            # its dumps are built from the event ring and phase timings,
            # so a recorder without telemetry would dump hollow files
            # exactly when the post-mortem needs them
            or os.environ.get("MXNET_FLIGHT_RECORDER", "")
            not in ("0", "", "false")
            or bool(os.environ.get("MXNET_FLIGHT_RECORDER_DIR"))
            # an armed hang watchdog (sentinel) implies telemetry the
            # same way: its whole progress feed is the phase hook, and
            # phase exits only reach hooks while telemetry records — a
            # watchdog without telemetry would see a healthy job as
            # eternally stalled and false-trip at the deadline floor
            or os.environ.get("MXNET_WATCHDOG", "")
            not in ("0", "", "false"))


def enabled():
    """True when the registry records (``MXNET_TELEMETRY=1`` or
    :func:`enable`); the one check every hot path makes."""
    return _enabled


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def _key(name, labels):
    if not labels:
        return (name, ())
    return (name, tuple(sorted((k, str(v)) for k, v in labels.items())))


# -- recording --------------------------------------------------------------
def inc(name, value=1, **labels):
    """Add ``value`` to counter ``name`` (``inc(name, 0)`` declares it at
    zero so a family is visible in ``snapshot()`` before its first
    increment)."""
    if not _enabled:
        return
    k = _key(name, labels)
    with _lock:
        _counters[k] = _counters.get(k, 0) + value


def declare(*names):
    """Declare counter families at zero so they are visible in
    ``snapshot()``/Prometheus before their first increment (``fit``
    does this for the resilience family; ``compile_cache`` for the
    persistent-cache family)."""
    for name in names:
        inc(name, 0)


def set_gauge(name, value, **labels):
    """Set gauge ``name`` to ``value`` (last write wins)."""
    if not _enabled:
        return
    with _lock:
        _gauges[_key(name, labels)] = value


class _Histogram:
    __slots__ = ("buckets", "counts", "count", "sum", "min", "max")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1: overflow (+Inf)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v):
        v = float(v)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v


def observe(name, value, buckets=None, **labels):
    """Record ``value`` into histogram ``name`` (bucket bounds fixed by
    the first observation)."""
    if not _enabled:
        return
    k = _key(name, labels)
    with _lock:
        h = _hists.get(k)
        if h is None:
            h = _hists[k] = _Histogram(buckets or DEFAULT_BUCKETS)
        h.observe(value)


def event(name, **fields):
    """Append one structured event (``{"ts", "event", **fields}``) to the
    in-memory JSONL log (bounded ring; ``dump_events`` exports)."""
    if not _enabled:
        return
    rec = {"ts": round(time.time(), 6), "event": name}
    rec.update(fields)
    with _lock:
        _events.append(rec)


def events_recent(n=100):
    """The newest ``n`` structured events (copies) — what the flight
    recorder folds into a crash dump."""
    with _lock:
        return [dict(r) for r in list(_events)[-int(n):]]


#: registered per-phase observers, each called as ``hook(family,
#: phase_name, seconds)`` from an ENABLED phase's exit.  Two consumers
#: exist today — the flight recorder's per-batch timing feed
#: (:mod:`mxnet_tpu.perfdebug`) and the training watchdog's progress
#: feed (:mod:`mxnet_tpu.sentinel`) — which is exactly why this is a
#: LIST: the old single ``set_phase_hook`` slot meant whoever installed
#: second silently evicted the other.  Stored as a tuple so the hot
#: path iterates a stable snapshot (one truthiness check when empty);
#: registration swaps the whole tuple under ``_lock``.
_phase_hooks = ()
#: the hook installed through the deprecated ``set_phase_hook`` alias
#: (so a second ``set_phase_hook`` call keeps its replace semantics
#: without evicting ``add_phase_hook`` registrations)
_set_alias_hook = None


def add_phase_hook(hook):
    """Register a phase observer (``hook(family, phase, seconds)``);
    duplicate registrations are ignored.  Returns ``hook`` so callers
    can hold it for :func:`remove_phase_hook`."""
    global _phase_hooks
    with _lock:
        if hook not in _phase_hooks:
            _phase_hooks = _phase_hooks + (hook,)
    return hook


def remove_phase_hook(hook):
    """Unregister a phase observer; unknown hooks are a no-op."""
    global _phase_hooks
    with _lock:
        _phase_hooks = tuple(h for h in _phase_hooks if h is not hook)


def set_phase_hook(hook):
    """Deprecated single-slot spelling: replaces only the hook a
    previous ``set_phase_hook`` installed (or clears it with ``None``)
    — registrations made through :func:`add_phase_hook` are never
    evicted.  New code should use ``add_phase_hook`` /
    ``remove_phase_hook``."""
    global _phase_hooks, _set_alias_hook
    with _lock:
        hooks = tuple(h for h in _phase_hooks if h is not _set_alias_hook)
        _set_alias_hook = hook
        if hook is not None:
            hooks = hooks + (hook,)
        _phase_hooks = hooks


class phase:
    """Time one training-loop phase: a histogram observation in
    ``<family>.phase_seconds{phase=<name>}`` and — when the profiler is
    running — a chrome-trace span via ``profiler.record``.

    Disabled-cheap like ``profiler.span``: the enabled check happens once
    in ``__init__`` and a disabled phase does no clock reads.  Note JAX
    dispatch is asynchronous, so device compute time is attributed to the
    first phase that blocks on results (see docs/observability.md) — in
    the sync-free fit loop that is the explicit ``sync`` phase (device
    metric reads, NaN-guard flag reads), which exists precisely so
    ``metric`` and friends time only their dispatch work.
    """

    __slots__ = ("_name", "_family", "_t0", "_on", "_prof")

    def __init__(self, name, family="fit"):
        self._prof = _profiler.running()
        self._on = _enabled or self._prof
        if self._on:
            self._name = name
            self._family = family

    def __enter__(self):
        if self._on:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._on:
            dt = time.perf_counter() - self._t0
            if _enabled:
                observe(self._family + ".phase_seconds", dt,
                        phase=self._name)
            if self._prof:
                end = _profiler._now_us()
                _profiler.record("%s:%s" % (self._family, self._name),
                                 "phase", end - dt * 1e6, end)
            if _phase_hooks:
                for hook in _phase_hooks:
                    hook(self._family, self._name, dt)
        return False


# -- derived reads ----------------------------------------------------------
def phase_totals(family="fit"):
    """``{phase: (sum_seconds, count)}`` for one family's phase
    histograms — the per-phase step-time breakdown consumers
    (``TelemetryReport``, ``bench.py``) read."""
    name = family + ".phase_seconds"
    out = {}
    with _lock:
        for (n, labels), h in _hists.items():
            if n == name:
                out[dict(labels).get("phase", "")] = (h.sum, h.count)
    return out


def counter_total(name):
    """Sum of counter ``name`` across all label sets (0 when absent)."""
    with _lock:
        return sum(v for (n, _), v in _counters.items() if n == name)


def gauge_value(name, **labels):
    """Current value of gauge ``name`` (None when unset)."""
    with _lock:
        return _gauges.get(_key(name, labels))


def hist_quantile(name, q, **labels):
    """Estimate the ``q``-quantile (0..1) of histogram ``name`` from its
    bucket counts — linear interpolation inside the target bucket, the
    observed min/max capping the first/overflow buckets.  What the
    serving layer's p50/p99 reads (and Prometheus' ``histogram_quantile``
    would compute from the same exposition); None when unobserved."""
    with _lock:
        h = _hists.get(_key(name, labels))
        if h is None or h.count == 0:
            return None
        target = q * h.count
        acc = 0
        lo = h.min
        for b, c in zip(h.buckets, h.counts):
            if acc + c >= target:
                if c == 0:
                    return min(lo, h.max)
                frac = (target - acc) / c
                return min(lo + (min(b, h.max) - lo) * max(0.0, frac),
                           h.max)
            acc += c
            lo = max(lo, b)
        return h.max  # overflow bucket: cap at the observed max


def hist_state(name, **labels):
    """Raw histogram state — bucket bounds, per-bucket counts (the last
    entry is the overflow bucket), total count/sum and observed min/max
    — or None when unobserved.  Windowed-quantile readers (the fleet
    controller's TTFT-p99 window) diff two snapshots' counts and feed
    the delta to :func:`quantile_from_counts`; cumulative
    :func:`hist_quantile` would smear the whole process history into
    the estimate."""
    with _lock:
        h = _hists.get(_key(name, labels))
        if h is None:
            return None
        return {"buckets": tuple(h.buckets), "counts": list(h.counts),
                "count": h.count, "sum": h.sum,
                "min": h.min, "max": h.max}


def quantile_from_counts(buckets, counts, q, lo=None, hi=None):
    """:func:`hist_quantile`'s estimator over caller-supplied bucket
    counts (e.g. the delta of two :func:`hist_state` reads).  ``lo`` /
    ``hi`` cap the first/overflow buckets the way the histogram's
    observed min/max do; they default to 0 and the last finite bound.
    None when the counts are empty."""
    total = sum(counts)
    if total <= 0:
        return None
    lo = 0.0 if lo is None else float(lo)
    hi = float(buckets[-1]) if hi is None else float(hi)
    target = q * total
    acc = 0
    cur = lo
    for b, c in zip(buckets, counts):
        if acc + c >= target:
            if c == 0:
                return min(cur, hi)
            frac = (target - acc) / c
            return min(cur + (min(b, hi) - cur) * max(0.0, frac), hi)
        acc += c
        cur = max(cur, b)
    return hi  # overflow bucket: cap at hi


# -- memory sampling --------------------------------------------------------
def sample_memory():
    """Sample device (HBM) memory stats from JAX into ``memory.device.*``
    gauges, plus the host max-RSS so the memory family exists even on
    backends (CPU) whose devices expose no ``memory_stats``."""
    if not _enabled:
        return
    try:
        import jax

        devices = jax.local_devices()
    except (ImportError, RuntimeError):
        devices = []
    for d in devices:
        stats_fn = getattr(d, "memory_stats", None)
        stats = None
        if stats_fn is not None:
            try:
                stats = stats_fn()
            except (RuntimeError, NotImplementedError):
                stats = None  # backend without allocator stats
        if not stats:
            continue
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in stats:
                set_gauge("memory.device.%s" % k, stats[k],
                          device=getattr(d, "id", 0))
    try:
        import resource
        import sys

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss unit is kilobytes on Linux but bytes on macOS
        if sys.platform != "darwin":
            rss *= 1024
        set_gauge("memory.host.max_rss_bytes", rss)
    except (ImportError, ValueError, OSError):  # non-POSIX host
        pass


# -- exporters --------------------------------------------------------------
def _label_str(labels):
    return ",".join("%s=%s" % kv for kv in labels)


def _hist_dict(h):
    cum, acc = {}, 0
    for b, c in zip(h.buckets, h.counts):
        acc += c
        cum["%g" % b] = acc
    cum["+Inf"] = acc + h.counts[-1]
    return {"count": h.count, "sum": h.sum, "min": h.min, "max": h.max,
            "mean": (h.sum / h.count) if h.count else 0.0, "buckets": cum}


def snapshot():
    """The whole registry as a nested dict:
    ``{enabled, counters: {name: {labels: v}}, gauges: {...},
    histograms: {name: {labels: {count,sum,min,max,mean,buckets}}},
    events: {count, recent}}``."""
    with _lock:
        counters, gauges, hists = {}, {}, {}
        for (n, labels), v in sorted(_counters.items()):
            counters.setdefault(n, {})[_label_str(labels)] = v
        for (n, labels), v in sorted(_gauges.items()):
            gauges.setdefault(n, {})[_label_str(labels)] = v
        for (n, labels), h in sorted(_hists.items()):
            hists.setdefault(n, {})[_label_str(labels)] = _hist_dict(h)
        return {"enabled": _enabled, "counters": counters, "gauges": gauges,
                "histograms": hists,
                "events": {"count": len(_events),
                           "recent": list(_events)[-100:]}}


def dump(path):
    """Write ``snapshot()`` as JSON; returns ``path``."""
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=1, default=str)
    return path


def dump_events(path):
    """Write the structured event log as JSONL (one event per line);
    returns ``path``."""
    with _lock:
        events = list(_events)
    with open(path, "w") as f:
        for rec in events:
            f.write(json.dumps(rec, default=str))
            f.write("\n")
    return path


def _prom_name(name):
    s = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    return s if s.startswith("mxnet_") else "mxnet_" + s


def _prom_esc(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _prom_labels(labels, extra=()):
    items = list(labels) + list(extra)
    if not items:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _prom_esc(v))
                             for k, v in items)


def _prom_num(v):
    v = float(v)
    return "%d" % int(v) if v.is_integer() else repr(v)


def prometheus_text():
    """The registry in Prometheus text-exposition format (counter /
    gauge / histogram types, cumulative ``le`` buckets)."""
    with _lock:
        counters = sorted(_counters.items())
        gauges = sorted(_gauges.items())
        hists = sorted(_hists.items())
    lines = []
    for kind, store in (("counter", counters), ("gauge", gauges)):
        seen = set()
        for (name, labels), v in store:
            pname = _prom_name(name)
            if pname not in seen:
                seen.add(pname)
                lines.append("# TYPE %s %s" % (pname, kind))
            lines.append("%s%s %s" % (pname, _prom_labels(labels),
                                      _prom_num(v)))
    seen = set()
    for (name, labels), h in hists:
        pname = _prom_name(name)
        if pname not in seen:
            seen.add(pname)
            lines.append("# TYPE %s histogram" % pname)
        acc = 0
        for b, c in zip(h.buckets, h.counts):
            acc += c
            lines.append("%s_bucket%s %d" % (
                pname, _prom_labels(labels, [("le", "%g" % b)]), acc))
        lines.append("%s_bucket%s %d" % (
            pname, _prom_labels(labels, [("le", "+Inf")]),
            acc + h.counts[-1]))
        lines.append("%s_sum%s %s" % (pname, _prom_labels(labels),
                                      _prom_num(h.sum)))
        lines.append("%s_count%s %d" % (pname, _prom_labels(labels),
                                        h.count))
    return "\n".join(lines) + "\n"


def write_prometheus(path):
    """Write :func:`prometheus_text` to ``path`` (e.g. for a node-exporter
    textfile collector); returns ``path``."""
    with open(path, "w") as f:
        f.write(prometheus_text())
    return path


def reset():
    """Clear all metrics and events (tests; enablement is unchanged)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _events.clear()


def _atexit_dump():  # pragma: no cover - exercised via subprocess test
    path = os.environ.get("MXNET_TELEMETRY_DUMP")
    if not path:
        return
    try:
        dump(path)
        dump_events(os.path.splitext(path)[0] + ".events.jsonl")
    except OSError as e:
        import logging

        logging.warning("telemetry: could not write %r at exit: %s",
                        path, e)


if os.environ.get("MXNET_TELEMETRY_DUMP"):
    import atexit

    atexit.register(_atexit_dump)
