"""RecordIO: binary-compatible dmlc record format + image record packing.

Reference: ``python/mxnet/recordio.py`` (API) over dmlc-core's C++
``RecordIOWriter/Reader`` (behavior recovered from call sites; the submodule
is empty — SURVEY preamble).  Format, preserved bit-for-bit so ``.rec``
shards interchange with the reference:

  record := uint32 magic (0xced7230a)
            uint32 lrec   (upper 3 bits: cflag, lower 29 bits: length)
            payload[length]
            pad to 4-byte boundary

Payloads containing the magic are split at each occurrence into a chain of
parts with cflag 1 (start) / 2 (middle) / 3 (end); cflag 0 marks a whole
record.  ``MXIndexedRecordIO`` keeps a ``key\\tposition`` text index for
random access (the reference's ``.idx`` files).

The TPU angle: RecordIO is the host-side half of the input pipeline — packed
shards are read/decoded/augmented on host CPU (``image.py``) and batches are
fed to the chip asynchronously (``io.PrefetchingIter``), the analog of the
reference's ``PrefetcherIter`` pinned-memory double buffering
(``src/io/iter_prefetcher.h:49``).
"""

from __future__ import annotations

import logging
import os
import struct
import threading
from collections import namedtuple

import numpy as np

from . import faults as _faults
from . import telemetry as _telemetry
from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img",
           "skipped_record_count", "reset_skipped_record_count"]

_KMAGIC = 0xced7230a
_STRUCT_U32 = struct.Struct("<I")


class _Truncated(MXNetError):
    """A record short-read (file ended inside a record): a torn tail when
    no later record boundary exists, mid-file corruption when one does."""

# process-wide tally of corrupt records skipped under
# MXNET_IO_SKIP_CORRUPT=1, across every reader (per-reader counts live on
# MXRecordIO.num_skipped); readers may sit on prefetch threads, hence the
# lock
_skip_lock = threading.Lock()
_total_skipped = 0


def _note_skip(uri, pos, err):
    global _total_skipped
    with _skip_lock:
        _total_skipped += 1
    _telemetry.inc("resilience.recordio_skipped")
    _telemetry.event("recordio_skip", uri=uri, pos=pos, error=str(err))
    logging.warning("recordio: skipping corrupt record in %s near byte %d "
                    "(%s)", uri, pos, err)


def skipped_record_count():
    """Corrupt records skipped process-wide (MXNET_IO_SKIP_CORRUPT=1)."""
    with _skip_lock:
        return _total_skipped


def reset_skipped_record_count():
    global _total_skipped
    with _skip_lock:
        _total_skipped = 0


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(lrec):
    return lrec >> 29, lrec & ((1 << 29) - 1)


class MXRecordIO:
    """Sequential RecordIO reader/writer (``flag`` = 'r' or 'w').

    ``skip_corrupt`` (default: the ``MXNET_IO_SKIP_CORRUPT`` env var):
    when truthy, a corrupt record (bad magic, short read, broken
    multi-part chain) is *skipped* — the reader rescans for the next
    record boundary, bumps ``num_skipped`` and the process-wide counter
    (:func:`skipped_record_count`) — instead of raising mid-epoch."""

    def __init__(self, uri, flag, skip_corrupt=None):
        self.uri = uri
        self.flag = flag
        self.record = None
        if skip_corrupt is None:
            skip_corrupt = os.environ.get(
                "MXNET_IO_SKIP_CORRUPT", "0") not in ("0", "", "false")
        self.skip_corrupt = skip_corrupt
        self.num_skipped = 0
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("invalid flag %r" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.record.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: broad-except — interpreter-shutdown GC
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["record"] = None
        d["_pos"] = self.record.tell() if self.is_open else 0
        d["is_open"] = False
        return d

    def __setstate__(self, d):
        pos = d.pop("_pos", 0)
        self.__dict__.update(d)
        self.open()
        self.record.seek(pos)

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.record.tell()

    # -- iterator-state protocol (docs/resilience.md "exact resume") ------
    def state_dict(self):
        """Byte position of the read stream — with ``load_state_dict``
        this lets RecordIO-backed data iterators resume a mid-epoch
        checkpoint at the exact next record."""
        if self.writable:
            raise MXNetError("state_dict is a reader-side protocol "
                             "(writer position is not resumable)")
        return {"type": type(self).__name__,
                "pos": self.record.tell() if self.is_open else 0,
                "num_skipped": self.num_skipped}

    def load_state_dict(self, state):
        if self.writable:
            raise MXNetError("load_state_dict on a writer")
        if not self.is_open:
            self.open()
        self.record.seek(int(state["pos"]))
        self.num_skipped = int(state.get("num_skipped", 0))

    def write(self, buf):
        """Write one record (bytes), splitting at embedded magics."""
        assert self.writable
        if not isinstance(buf, bytes):
            buf = bytes(buf)
        magic_bytes = _STRUCT_U32.pack(_KMAGIC)
        # find magic occurrences to escape
        parts = []
        start = 0
        while True:
            i = buf.find(magic_bytes, start)
            if i < 0:
                parts.append(buf[start:])
                break
            parts.append(buf[start:i])
            start = i + 4
        n = len(parts)
        for j, part in enumerate(parts):
            if n == 1:
                cflag = 0
            elif j == 0:
                cflag = 1
            elif j == n - 1:
                cflag = 3
            else:
                cflag = 2
            self.record.write(magic_bytes)
            self.record.write(_STRUCT_U32.pack(_encode_lrec(cflag, len(part))))
            self.record.write(part)
            pad = (4 - len(part) % 4) % 4
            if pad:
                self.record.write(b"\x00" * pad)

    def _read_part(self):
        head = self.record.read(4)
        if len(head) == 0:
            return None, None  # clean EOF on a record boundary
        if len(head) < 4:
            raise _Truncated("short read: truncated record magic at %d"
                             % (self.record.tell() - len(head)))
        magic, = _STRUCT_U32.unpack(head)
        if magic != _KMAGIC:
            raise MXNetError("invalid record magic %x at %d"
                             % (magic, self.record.tell() - 4))
        lbuf = self.record.read(4)
        if len(lbuf) < 4:
            raise _Truncated("short read: truncated record length at %d"
                             % (self.record.tell() - len(lbuf)))
        lrec, = _STRUCT_U32.unpack(lbuf)
        cflag, length = _decode_lrec(lrec)
        data = self.record.read(length)
        if len(data) < length:
            raise _Truncated(
                "short read: record payload truncated (%d of %d bytes) "
                "at %d" % (len(data), length, self.record.tell()))
        pad = (4 - length % 4) % 4
        if pad:
            self.record.read(pad)
        return cflag, data

    def _read_one(self):
        """Read one record; None at EOF; MXNetError on corruption."""
        cflag, data = self._read_part()
        if cflag is None:
            return None
        if cflag == 0:
            return data
        if cflag != 1:
            raise MXNetError("corrupt record chain (cflag=%d)" % cflag)
        magic_bytes = _STRUCT_U32.pack(_KMAGIC)
        out = [data]
        while True:
            cflag, data = self._read_part()
            if cflag is None:
                raise MXNetError("EOF inside multi-part record")
            out.append(magic_bytes)  # each split consumed one magic
            out.append(data)
            if cflag == 3:
                break
            if cflag != 2:
                raise MXNetError("corrupt record chain (cflag=%d)" % cflag)
        return b"".join(out)

    def _resync(self):
        """After a corrupt record: scan forward for the next 4-byte-
        aligned magic (payload magics are escaped on write, so any
        aligned magic is a real boundary).  False at EOF."""
        magic_bytes = _STRUCT_U32.pack(_KMAGIC)
        pos = self.record.tell()
        pos += (-pos) % 4  # records are 4-byte aligned
        while True:
            self.record.seek(pos)
            chunk = self.record.read(1 << 16)
            if len(chunk) < 4:
                return False
            i = chunk.find(magic_bytes)
            while i >= 0 and (pos + i) % 4 != 0:
                i = chunk.find(magic_bytes, i + 1)
            if i >= 0:
                self.record.seek(pos + i)
                return True
            pos += len(chunk) - 3  # overlap: magic may straddle chunks

    def read(self):
        """Read one record; None at EOF.

        With ``skip_corrupt`` armed a corrupt record is counted and
        skipped (reader resyncs to the next boundary); otherwise the
        corruption raises MXNetError."""
        assert not self.writable
        while True:
            pos = self.record.tell()
            try:
                if _faults.should_fire("recordio.read"):
                    self._read_one()  # consume the record the fault eats
                    raise MXNetError(
                        "fault 'recordio.read': record at %d treated as "
                        "corrupt" % pos)
                return self._read_one()
            except MXNetError as e:
                if not self.skip_corrupt:
                    if not isinstance(e, _Truncated):
                        raise
                    # a short read with no later record boundary is a torn
                    # tail (writer killed mid-append) — the pre-resilience
                    # reader treated that as EOF, so ending the epoch
                    # cleanly (with a warning) is not a behavior change;
                    # a boundary AFTER the short read means real mid-file
                    # corruption, which stays fail-loud by default
                    self.record.seek(pos + 4)
                    if self._resync():
                        self.record.seek(pos)
                        raise
                    logging.warning(
                        "recordio: ignoring truncated trailing record in "
                        "%s near byte %d (%s)", self.uri, pos, e)
                    return None
                self.num_skipped += 1
                _note_skip(self.uri, pos, e)
                # rescan from just past the failed record's header — a
                # corrupt *length* field may have dragged the cursor far
                # past the next good record (even to EOF)
                self.record.seek(pos + 4)
                if not self._resync():
                    return None


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a ``key\\tposition`` index for random access
    (reference ``python/mxnet/recordio.py`` ``MXIndexedRecordIO``)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable:
            if os.path.isfile(idx_path):
                with open(idx_path) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        if len(parts) != 2:
                            continue
                        key = key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)
            else:
                # no .idx: rebuild positions with the native boundary
                # scanner (src/native.cc MXRecordIOScan); keys become 0..n-1
                from .native import recordio_scan

                try:
                    offsets = recordio_scan(uri)
                except IOError:
                    # corrupt/truncated shard: leave keys empty so callers
                    # fall back to sequential MXRecordIO access
                    offsets = None
                if offsets is not None:
                    for i, off in enumerate(offsets):
                        key = key_type(i)
                        self.idx[key] = off
                        self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write("%s\t%d\n" % (key, self.idx[key]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        """Random access returns key ``idx``'s record or raises — the
        sequential ``skip_corrupt`` resync must not kick in here, or a
        corrupt record would be silently *substituted* by whatever record
        follows it on disk."""
        self.seek(idx)
        if _faults.should_fire("recordio.read"):
            raise MXNetError("fault 'recordio.read': record %r treated "
                             "as corrupt" % (idx,))
        return self._read_one()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


# ---------------------------------------------------------------------------
# image record header (reference IRHeader: uint32 flag, float label,
# uint64 id, uint64 id2 → '<IfQQ'; flag>0 appends flag extra label floats)
# ---------------------------------------------------------------------------

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack IRHeader + byte payload into one record buffer."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, header.flag, float(header.label),
                          header.id, header.id2)
        return hdr + s
    label = np.asarray(header.label, dtype=np.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """Unpack a record buffer into (IRHeader, payload bytes)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = np.frombuffer(s[:flag * 4], dtype=np.float32)
        s = s[flag * 4:]
    header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode HWC uint8 image (BGR, as OpenCV) and pack with header."""
    import cv2

    if img_fmt in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    else:
        encode_params = None
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    if not ret:
        raise MXNetError("failed to encode image")
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=1):
    """Unpack a record into (IRHeader, decoded HWC uint8 BGR image)."""
    import cv2

    header, img_bytes = unpack(s)
    img = cv2.imdecode(np.frombuffer(img_bytes, dtype=np.uint8), iscolor)
    return header, img
