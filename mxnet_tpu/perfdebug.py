"""Performance attribution + crash flight recorder.

BENCH regressions used to be unexplainable: the framework could time
*phases* (telemetry ``fit.phase_seconds``) but not attribute cost — a
"resnet-50 inference is 38% slower than best" row said nothing about
WHICH compiled executable got slower or bigger.  TVM's premise (Chen et
al., 2018) is that op-level cost profiles are the prerequisite for any
fusion/layout tuning; this module is that layer for the XLA executor:

* **Executable attribution** — on every jit build (executor kinds
  ``predict``/``train``/``train_sgd``/placement segments/the fused
  update), capture XLA ``cost_analysis()`` (flops, bytes accessed) and
  ``memory_analysis()`` (argument/output/temp/generated-code bytes —
  the HBM breakdown) per compiled executable, keyed by executor name +
  kind + input-shape signature.  :func:`report` lists every executable;
  telemetry gains ``perf.executable.*`` gauges.
* **HLO fingerprinting** — each lowered program's text is normalized
  (naming-noise annotations stripped) and hashed; re-building the same
  (name, kind, shapes) key with different HLO records a fingerprint
  *change* (:func:`changes`, ``hlo.fingerprint_change`` telemetry
  event, ``perf.fingerprint_changes`` counter).  "Regression vs best"
  becomes "these 2 of 7 executables changed".
  :func:`save_fingerprints` / :func:`diff_fingerprints` compare across
  runs/commits; ``bench.py`` / ``bench_extra.py`` persist per-model
  fingerprints in their JSON rows for the same purpose.
* **Live MFU / HBM gauges** — :func:`note_throughput` (called by
  ``Speedometer`` at its log cadence, no extra syncs) combines the
  latest train-step executable's measured flops with the chip's rated
  peak into the ``perf.mfu_pct`` gauge; captures refresh
  ``perf.hbm_peak_bytes``.  Both flow into ``TelemetryReport`` epoch
  lines and the serving ``/metrics`` exposition automatically.
* **Flight recorder** — a bounded in-memory ring of phase timings
  (hooked into ``telemetry.phase``), fingerprint changes and resilience
  marks, dumped ATOMICALLY (``base.atomic_write``) together with the
  recent telemetry events, phase totals and the attribution table on
  crash, NaN-policy trip, ``TrainingPreempted`` and SIGTERM drain — so
  a post-mortem of a chaos-harness kill carries the perf context that
  otherwise evaporates with the process.

Cost model: attribution is OFF by default (``MXNET_PERF_ATTRIB=1`` or
:func:`enable`); when on, each executable's FIRST call additionally
AOT-lowers + compiles the same program for analysis — roughly doubling
one-time compile cost, never touching steady-state dispatch.  The
flight recorder (``MXNET_FLIGHT_RECORDER=1`` or a
``MXNET_FLIGHT_RECORDER_DIR``) costs one ring append per recorded
phase; disabled, both are an early-returning check.

See docs/observability.md "Performance attribution" / "Flight
recorder".
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time
from collections import deque

from . import telemetry as _telemetry
from . import tracing as _tracing
from .base import atomic_write

__all__ = [
    "enabled", "enable", "disable", "capture", "analyze_signature",
    "instrument", "report", "report_text", "fingerprints", "changes",
    "save_fingerprints", "diff_fingerprints", "reset",
    "device_peak_tflops", "step_flops", "note_throughput",
    "flight_enabled", "flight_record", "flight_dump",
    "PEAK_TFLOPS_BY_KIND",
]

_log = logging.getLogger("mxnet_tpu.perfdebug")

#: bf16 dense peak TFLOP/s by PJRT ``device_kind`` (published chip
#: specs) — the denominator of every MFU figure.  ``MXNET_PEAK_TFLOPS``
#: (or the bench harness' ``BENCH_PEAK_TFLOPS``) overrides for kinds
#: not listed.
PEAK_TFLOPS_BY_KIND = {
    "TPU v2": 46.0,
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v4 lite": 138.0,
    "TPU v5": 459.0,        # v5p
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v6 lite": 918.0,   # v6e / Trillium
    "TPU v6e": 918.0,
}

_lock = threading.Lock()
_entries = {}      # (exec, kind, sig) -> attribution entry dict
_changes = []      # fingerprint-change records, in detection order
_latest_step = None  # newest train-family entry (MFU numerator)

_enabled_flag = None   # tri-state: None = follow env, True/False forced


# -- enablement -------------------------------------------------------------
def enabled():
    """True when executable attribution records (``MXNET_PERF_ATTRIB=1``
    or :func:`enable`); consulted once per jit BUILD, never per
    dispatch."""
    if _enabled_flag is not None:
        return _enabled_flag
    return os.environ.get("MXNET_PERF_ATTRIB", "0") \
        not in ("0", "", "false")


def enable():
    global _enabled_flag
    _enabled_flag = True


def disable():
    global _enabled_flag
    _enabled_flag = False


# -- lowering / analysis helpers --------------------------------------------
def _abstractify(tree):
    """Shapes+dtypes only: safe to build AFTER a donating dispatch (aval
    metadata survives donation) and holds no device buffers."""
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(leaf, tree)


#: annotations stripped before hashing: jax records python-side arg/
#: result names (parameter dict keys) into the StableHLO text — naming
#: noise (auto-generated symbol names differ per build) that would flag
#: identical computations as changed
_HLO_NOISE_RE = re.compile(
    r'\s*\{jax\.(?:result_info|arg_info)[^}]*\}')


def fingerprint_text(hlo_text):
    """Stable 16-hex digest of one lowered program, naming noise
    stripped."""
    normalized = _HLO_NOISE_RE.sub("", hlo_text)
    return hashlib.sha256(normalized.encode()).hexdigest()[:16]


def _shape_sig(args, kwargs):
    """Short stable hash of the call's input avals — the 'shape
    signature' half of an attribution key."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    parts = []
    for x in leaves:
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            parts.append("%s%s" % (getattr(x.dtype, "name", x.dtype),
                                   tuple(x.shape)))
        else:
            parts.append(repr(x))
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:8]


def _first(cost):
    # older jax returns a one-dict-per-device list from cost_analysis
    if isinstance(cost, (list, tuple)):
        return cost[0] if cost else None
    return cost


_MEM_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)


def _analyze_lowered(lowered):
    """(fingerprint, flops, bytes_accessed, hbm_breakdown) of one
    lowered program; compiles it for the cost/memory numbers (falling
    back to pre-compile cost analysis where the backend supports it)."""
    fp = fingerprint_text(lowered.as_text())
    cost = None
    mem = {}
    try:
        compiled = lowered.compile()
    except Exception:
        compiled = None
    if compiled is not None:
        try:
            cost = _first(compiled.cost_analysis())
        except Exception:
            cost = None
        try:
            m = compiled.memory_analysis()
            mem = {name: int(getattr(m, attr))
                   for name, attr in _MEM_FIELDS if hasattr(m, attr)}
        except Exception:
            mem = {}
    if cost is None:
        try:
            cost = _first(lowered.cost_analysis())
        except Exception:
            cost = None
    flops = None
    bytes_accessed = None
    if cost:
        if cost.get("flops"):
            flops = float(cost["flops"])
        if cost.get("bytes accessed"):
            bytes_accessed = float(cost["bytes accessed"])
    return fp, flops, bytes_accessed, mem


def _hbm_total(mem):
    if not mem:
        return None
    return sum(mem.get(k, 0) for k in ("argument_bytes", "output_bytes",
                                       "temp_bytes",
                                       "generated_code_bytes"))


def _device_peak_bytes():
    """Live allocator high-water mark, when the backend exposes one."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return None
    peak = None
    for d in devices:
        stats_fn = getattr(d, "memory_stats", None)
        if stats_fn is None:
            continue
        try:
            stats = stats_fn() or {}
        except Exception:
            continue
        v = stats.get("peak_bytes_in_use")
        if v is not None:
            peak = max(peak or 0, int(v))
    return peak


def _refresh_hbm_gauge():
    """``perf.hbm_peak_bytes``: the device allocator's high-water mark
    when available (TPU), else the largest captured executable's static
    footprint (args+outputs+temp+code)."""
    peak = _device_peak_bytes()
    if peak is None:
        with _lock:
            totals = [_hbm_total(e["hbm"]) for e in _entries.values()]
        totals = [t for t in totals if t]
        peak = max(totals) if totals else None
    if peak is not None:
        _telemetry.set_gauge("perf.hbm_peak_bytes", peak)
    return peak


# -- capture ----------------------------------------------------------------
def capture(name, kind, lower_fn, args, kwargs=None):
    """Attribute one freshly built executable: AOT-lower + compile the
    program via ``lower_fn`` (abstractified ``args``/``kwargs``),
    record cost/memory/fingerprint under key ``(name, kind, shape
    signature)``, and detect fingerprint changes against any previous
    build of the same key.  Never raises — attribution failure must not
    break execution.  Returns the entry dict or None."""
    if not enabled():
        return None
    try:
        return _capture(name, str(kind), lower_fn, args, kwargs or {})
    except Exception as e:
        _log.debug("perfdebug: capture failed for %s/%s: %s", name, kind, e)
        return None


def _capture(name, kind, lower_fn, args, kwargs):
    t0 = time.perf_counter()
    sds_args = _abstractify(args)
    sds_kwargs = _abstractify(kwargs)
    lowered = lower_fn(*sds_args, **sds_kwargs)
    fp, flops, bytes_accessed, mem = _analyze_lowered(lowered)
    sig = _shape_sig(sds_args, sds_kwargs)
    entry = {
        "exec": name, "kind": kind, "shapes": sig,
        "fingerprint": fp, "flops": flops,
        "bytes_accessed": bytes_accessed, "hbm": mem,
        "hbm_total_bytes": _hbm_total(mem), "builds": 1,
    }
    change = None
    global _latest_step
    with _lock:
        prev = _entries.get((name, kind, sig))
        if prev is not None:
            entry["builds"] = prev["builds"] + 1
            if prev["fingerprint"] != fp:
                change = {"ts": round(time.time(), 6), "exec": name,
                          "kind": kind, "shapes": sig,
                          "old": prev["fingerprint"], "new": fp,
                          "old_flops": prev["flops"],
                          "new_flops": flops}
                _changes.append(change)
        _entries[(name, kind, sig)] = entry
        if kind.startswith("train"):
            _latest_step = entry
    if change is not None:
        _telemetry.inc("perf.fingerprint_changes")
        _telemetry.event("hlo.fingerprint_change", **{
            k: v for k, v in change.items() if k != "ts"})
        flight_record("fingerprint_change", **{
            k: v for k, v in change.items() if k != "ts"})
        _log.warning(
            "perfdebug: executable %s/%s@%s changed HLO fingerprint "
            "%s -> %s (flops %s -> %s)", name, kind, sig, change["old"],
            fp, change["old_flops"], flops)
    if flops is not None:
        _telemetry.set_gauge("perf.executable.flops", flops,
                             exec=name, kind=kind)
    if bytes_accessed is not None:
        _telemetry.set_gauge("perf.executable.bytes_accessed",
                             bytes_accessed, exec=name, kind=kind)
    ht = _hbm_total(mem)
    if ht is not None:
        _telemetry.set_gauge("perf.executable.hbm_bytes", ht,
                             exec=name, kind=kind)
    _refresh_hbm_gauge()
    _telemetry.observe("perf.attrib_seconds", time.perf_counter() - t0)
    return entry


def analyze_signature(sig):
    """One-shot attribution of an abstract call signature ``(fn,
    abstract_args)`` — the shape ``Module._last_bulk_sig`` stores.  Used
    by the bench harnesses to stamp ``hlo_fingerprint`` /
    ``cost_gflops`` / ``hbm_peak_bytes`` onto their JSON rows; one
    lower+compile covers fingerprint AND cost.  Returns a dict or
    None."""
    if sig is None:
        return None
    fn, args = sig
    try:
        lowered = fn.lower(*args)
        fp, flops, bytes_accessed, mem = _analyze_lowered(lowered)
    except Exception as e:
        _log.debug("perfdebug: analyze_signature failed: %s", e)
        return None
    return {"fingerprint": fp, "flops": flops,
            "bytes_accessed": bytes_accessed, "hbm": mem,
            "hbm_peak_bytes": _device_peak_bytes() or _hbm_total(mem)}


class _FirstCallHook:
    """Minimal first-call wrapper for jitted functions built outside
    the executor's ``_get_fn`` path (e.g. ``Module``'s fused
    multi-tensor update): ``hook(fn, args, kwargs, seconds)`` runs once
    after the first call, then the wrapper is one boolean check per
    dispatch.  Shared by perfdebug attribution and compile_cache
    manifest recording (:func:`first_call_hook`)."""

    __slots__ = ("_fn", "_hook", "_pending")

    def __init__(self, fn, hook):
        self._fn = fn
        self._hook = hook
        self._pending = True

    def __call__(self, *args, **kwargs):
        if not self._pending:
            return self._fn(*args, **kwargs)
        self._pending = False
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        self._hook(self._fn, args, kwargs, time.perf_counter() - t0)
        return out

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)


def first_call_hook(fn, hook):
    """Wrap jitted ``fn`` so ``hook(fn, args, kwargs, seconds)`` fires
    once after its first call."""
    return _FirstCallHook(fn, hook)


def instrument(fn, name, kind):
    """Wrap jitted ``fn`` so its first call is attributed; returns
    ``fn`` unchanged when attribution is disabled."""
    if not enabled():
        return fn
    return _FirstCallHook(
        fn, lambda f, args, kwargs, _dt: capture(name, kind, f.lower,
                                                 args, kwargs))


# -- reads ------------------------------------------------------------------
def _key_str(key):
    return "%s/%s@%s" % key


def report():
    """Every captured executable as a list of dicts (exec, kind, shapes,
    fingerprint, flops, bytes_accessed, hbm breakdown, builds), sorted
    by key — the table a bench delta is pinned against."""
    with _lock:
        items = sorted(_entries.items())
    return [dict(e, hbm=dict(e["hbm"])) for _k, e in items]


def report_text():
    """:func:`report` formatted for humans/logs."""
    rows = report()
    if not rows:
        return "perfdebug: no executables captured " \
            "(MXNET_PERF_ATTRIB=1 to enable)"
    head = ("executable", "kind", "shapes", "fingerprint", "gflops",
            "mb_accessed", "hbm_mb", "builds")
    table = [head]
    for e in rows:
        table.append((
            e["exec"], e["kind"], e["shapes"], e["fingerprint"],
            "%.3f" % (e["flops"] / 1e9) if e["flops"] else "-",
            "%.1f" % (e["bytes_accessed"] / 1e6)
            if e["bytes_accessed"] else "-",
            "%.1f" % (e["hbm_total_bytes"] / 1e6)
            if e["hbm_total_bytes"] else "-",
            str(e["builds"])))
    widths = [max(len(r[i]) for r in table) for i in range(len(head))]
    return "\n".join("  ".join(c.ljust(w) for c, w in zip(r, widths))
                     for r in table)


def fingerprints():
    """``{"exec/kind@shapes": fingerprint}`` for every captured
    executable."""
    with _lock:
        return {_key_str(k): e["fingerprint"]
                for k, e in sorted(_entries.items())}


def changes():
    """Fingerprint changes detected this process, in order."""
    with _lock:
        return [dict(c) for c in _changes]


def _write_text(tmp, payload):
    with open(tmp, "w") as f:
        f.write(payload)


def save_fingerprints(path):
    """Persist :func:`fingerprints` as JSON (atomic) for a cross-run /
    cross-commit :func:`diff_fingerprints`; returns ``path``."""
    payload = json.dumps(fingerprints(), indent=1, sort_keys=True)
    atomic_write(path, lambda tmp: _write_text(tmp, payload))
    return path


def diff_fingerprints(path):
    """Compare the current fingerprints against a
    :func:`save_fingerprints` file: ``{"changed": {key: (old, new)},
    "added": [...], "removed": [...]}`` — the "these 2 of 7 executables
    changed" answer across commits."""
    with open(path) as f:
        old = json.load(f)
    now = fingerprints()
    return {
        "changed": {k: (old[k], v) for k, v in now.items()
                    if k in old and old[k] != v},
        "added": sorted(k for k in now if k not in old),
        "removed": sorted(k for k in old if k not in now),
    }


# -- live MFU ---------------------------------------------------------------
def device_peak_tflops(device=None):
    """Rated bf16 dense peak of ``device`` (default: first local
    device): ``MXNET_PEAK_TFLOPS`` / ``BENCH_PEAK_TFLOPS`` override,
    else the :data:`PEAK_TFLOPS_BY_KIND` table; None when unknown."""
    env = os.environ.get("MXNET_PEAK_TFLOPS") \
        or os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if device is None:
        try:
            import jax

            devices = jax.local_devices()
            device = devices[0] if devices else None
        except Exception:
            device = None
    kind = getattr(device, "device_kind", "") or ""
    return PEAK_TFLOPS_BY_KIND.get(kind)


def step_flops():
    """Measured flops of the newest captured train-family executable
    (the whole fused/two-phase training step), or None."""
    with _lock:
        if _latest_step is None:
            return None
        return _latest_step["flops"]


def note_throughput(samples_per_sec, batch_size):
    """Fold a measured training rate into the live ``perf.mfu_pct``
    gauge: (samples/sec x flops/sample) / rated peak.  Called by
    ``Speedometer`` at its log cadence — the rate is already measured,
    so this costs no extra device sync.  Returns the MFU percent, or
    None when step flops or the chip peak are unknown."""
    fl = step_flops()
    if not fl or not batch_size or not samples_per_sec:
        return None
    peak = device_peak_tflops()
    if not peak:
        return None
    tflops = samples_per_sec * (fl / float(batch_size)) / 1e12
    mfu = 100.0 * tflops / peak
    _telemetry.set_gauge("perf.mfu_pct", mfu)
    _telemetry.set_gauge("perf.tflops", tflops)
    return mfu


# -- flight recorder --------------------------------------------------------
_flight_lock = threading.Lock()
_flight = deque()
_flight_seq = [0]
_flight_flag = None  # tri-state like _enabled_flag


def _flight_dir():
    return os.environ.get("MXNET_FLIGHT_RECORDER_DIR", "")


_flight_size_cache = (None, 512)  # (raw env value, parsed size)


def _flight_size():
    """Ring capacity, memoized on the raw env string so the per-append
    cost is one dict get + compare, not an int() parse."""
    global _flight_size_cache
    raw = os.environ.get("MXNET_FLIGHT_RECORDER_SIZE", "")
    if raw != _flight_size_cache[0]:
        try:
            size = max(16, int(raw or 512))
        except ValueError:
            size = 512
        _flight_size_cache = (raw, size)
    return _flight_size_cache[1]


def flight_enabled():
    """True when the flight recorder rings/dumps:
    ``MXNET_FLIGHT_RECORDER=1``, a ``MXNET_FLIGHT_RECORDER_DIR``, or
    :func:`enable_flight_recorder`."""
    if _flight_flag is not None:
        return _flight_flag
    if os.environ.get("MXNET_FLIGHT_RECORDER", "") \
            not in ("", "0", "false"):
        return True
    return bool(_flight_dir())


def enable_flight_recorder():
    global _flight_flag
    _flight_flag = True
    # dumps are built from telemetry's event ring + phase timings: an
    # armed recorder over disabled telemetry would record nothing (the
    # env-armed spelling gets the same implication at telemetry import)
    _telemetry.enable()


def disable_flight_recorder():
    global _flight_flag
    _flight_flag = False


def flight_record(kind, **fields):
    """Append one record to the bounded in-memory ring (phase timings
    arrive here automatically through the telemetry phase hook)."""
    if not flight_enabled():
        return
    _flight_append(kind, fields)


def _flight_append(kind, fields):
    rec = {"ts": round(time.time(), 6), "kind": kind}
    rec.update(fields)
    with _flight_lock:
        _flight.append(rec)
        limit = _flight_size()
        while len(_flight) > limit:
            _flight.popleft()


def _telemetry_phase_hook(family, phase, seconds):
    # installed into telemetry.phase at import: each timed phase becomes
    # one ring record, so a dump carries the LAST batches' per-phase
    # durations, not just lifetime histograms.  ONE enablement check
    # here, then straight to the append — this runs a few times per
    # batch on the sync-free fit hot loop
    if flight_enabled():
        _flight_append("phase", {"family": family, "phase": phase,
                                 "seconds": round(seconds, 6)})


_telemetry.add_phase_hook(_telemetry_phase_hook)


def flight_dump(reason, **fields):
    """Dump the flight recorder atomically to
    ``MXNET_FLIGHT_RECORDER_DIR`` (default ``.``): the ring, the recent
    telemetry events, per-family phase totals, the attribution table,
    fingerprints and changes, and the perf gauges.  Called on crash,
    NaN-policy trip, preemption and SIGTERM drain; never raises.
    Returns the dump path, or None when disabled/failed."""
    if not flight_enabled():
        return None
    try:
        return _flight_dump_impl(reason, fields)
    except Exception as e:
        _log.warning("perfdebug: flight-recorder dump failed: %s", e)
        return None


def _flight_dump_impl(reason, fields):
    directory = _flight_dir() or "."
    if directory and not os.path.isdir(directory):
        os.makedirs(directory, exist_ok=True)
    with _flight_lock:
        records = list(_flight)
        _flight_seq[0] += 1
        seq = _flight_seq[0]
    phase_totals = {}
    for family in ("fit", "bulk", "serving", "bench", "io"):
        totals = _telemetry.phase_totals(family)
        if totals:
            phase_totals[family] = {
                ph: {"seconds": s, "count": n}
                for ph, (s, n) in sorted(totals.items())}
    payload = {
        "reason": reason,
        "detail": fields,
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "records": records,
        "events": _telemetry.events_recent(100),
        "phase_totals": phase_totals,
        "attribution": report(),
        "fingerprints": fingerprints(),
        "fingerprint_changes": changes(),
        "gauges": {
            "perf.mfu_pct": _telemetry.gauge_value("perf.mfu_pct"),
            "perf.hbm_peak_bytes":
                _telemetry.gauge_value("perf.hbm_peak_bytes"),
        },
    }
    safe_reason = re.sub(r"[^A-Za-z0-9_.-]", "_", str(reason))[:40]
    spans = _tracing.spans_recent() if _tracing.enabled() else ()
    if spans:
        # the span ring rides every dump as ndjson (one span per line,
        # joinable against the events' trace_id fields) — a post-mortem
        # of a failover carries the request trees that crossed it
        span_path = os.path.join(
            directory, "spans-%d-%04d-%s.ndjson"
            % (os.getpid(), seq, safe_reason))
        span_blob = "".join(json.dumps(s, default=str) + "\n"
                            for s in spans)
        atomic_write(span_path, lambda tmp: _write_text(tmp, span_blob),
                     durable=False)
        payload["span_dump"] = span_path
    path = os.path.join(directory, "flightrec-%d-%04d-%s.json"
                        % (os.getpid(), seq, safe_reason))
    blob = json.dumps(payload, indent=1, default=str)
    # durable=False: the dump races process death by design — atomic
    # against a torn write, but an fsync stall must not eat the drain
    # window
    atomic_write(path, lambda tmp: _write_text(tmp, blob), durable=False)
    _telemetry.event("flight_recorder.dump", reason=reason, path=path)
    _log.warning("perfdebug: flight recorder dumped to %s (reason=%s)",
                 path, reason)
    return path


def reset():
    """Clear attribution entries, change log and the flight ring
    (tests; enablement is unchanged)."""
    global _latest_step
    with _lock:
        _entries.clear()
        _changes.clear()
        _latest_step = None
    with _flight_lock:
        _flight.clear()
