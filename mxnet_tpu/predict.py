"""Standalone minimal inference API — the C predict ABI analog.

Reference: ``src/c_api/c_predict_api.cc`` + ``include/mxnet/c_predict_api.h``
(``MXPredCreate``/``MXPredSetInput``/``MXPredForward``/``MXPredGetOutput``/
``MXPredReshape``/``MXPredGetOutputShape``/``MXPredFree``) — the deliberately
tiny serving surface that ``amalgamation/`` ships to mobile and that the
matlab binding sits on (SURVEY §3.4).

Same contract here: construct from a saved symbol JSON string + a params
blob (bytes or path), bind once for fixed input shapes with ``grad_req
= null``, then ``set_input → forward → get_output``.  The whole forward is
one cached XLA computation; ``reshape`` re-jits under the shape-keyed
cache exactly like the reference's shared-memory rebind.
"""

from __future__ import annotations

import collections as _collections
import io as _io
import os as _os

import numpy as np

from . import ndarray as nd
from . import symbol as _symbol
from . import telemetry as _telemetry
from .base import MXNetError
from .context import Context, cpu

__all__ = ["Predictor", "load_ndarray_file", "create"]


def load_ndarray_file(blob):
    """Parse a params blob (bytes or filename) -> dict name->numpy.

    The analog of ``MXNDListCreate`` over ``NDArray::Load``'s magic-header
    dict format (``include/mxnet/ndarray.h:333-347``); the dmlc stream is
    the on-disk format (``ndarray.save``), with auto-detected fallback to
    this framework's earlier npz container.
    """
    import struct as _struct

    if isinstance(blob, (bytes, bytearray)):
        fh = _io.BytesIO(bytes(blob))
    else:
        fh = open(nd._load_path(blob), "rb")
    with fh:
        head = fh.read(8)
        fh.seek(0)
        if len(head) == 8 and \
                _struct.unpack("<Q", head)[0] == nd._DMLC_MAGIC:
            # stream straight from the handle: no second in-memory copy
            names, arrays = nd._load_dmlc(fh)
            if not names:
                names = ["%09d" % i for i in range(len(arrays))]
            return {k: a.asnumpy() for k, a in zip(names, arrays)}
        with np.load(fh) as f:
            return {k[2:] if k[:2] in ("d:", "l:") else k: np.asarray(f[k])
                    for k in f.files}


class Predictor:
    """``MXPredCreate`` analog (c_predict_api.cc ``MXAPIPredictor``)."""

    def __init__(self, symbol_json, param_blob, input_shapes, ctx=None,
                 output_index=None):
        if isinstance(symbol_json, _symbol.Symbol):
            sym = symbol_json
        else:
            sym = _symbol.load_json(symbol_json)
        if output_index is not None:  # MXPredCreatePartialOut
            outs = sym.get_internals()
            names = outs.list_outputs()
            sym = outs[names[output_index]]
        self._sym = sym
        self._ctx = ctx if isinstance(ctx, Context) else cpu()
        params = {}
        if param_blob is not None:
            raw = load_ndarray_file(param_blob)
            # reference accepts both plain and arg:/aux: prefixed keys
            for k, v in raw.items():
                if k.startswith(("arg:", "aux:")):
                    k = k[4:]
                params[k] = v
        self._params = params
        try:
            cap = int(_os.environ.get("MXNET_PRED_CACHE_SIZE", "16"))
        except ValueError:
            cap = 16
        #: bound on retained shape-specialized executors (each holds one
        #: compiled XLA program + its device buffers).  0 disables
        #: caching: every reshape rebinds and recompiles, the pre-LRU
        #: behavior.
        self._cache_cap = max(0, cap)
        self._exec_cache = _collections.OrderedDict()
        self._bind(dict(input_shapes))

    @staticmethod
    def _shape_key(shapes):
        return tuple(sorted((k, tuple(v)) for k, v in shapes.items()))

    @staticmethod
    def _is_weight(name, input_shapes):
        return name not in input_shapes \
            and not (name == "label" or name.endswith("_label"))

    def _bind(self, input_shapes, _from_exec=None):
        """Bind for ``input_shapes`` through the bounded shape-keyed
        executor cache (LRU, ``MXNET_PRED_CACHE_SIZE``, default 16).

        Under real traffic with varied shapes the unbounded reference
        behavior — every distinct shape compiles an executor retained
        forever — is an OOM; the LRU keeps the jit cache (and its device
        buffers) bounded while round-robin over a declared bucket set
        (serving) stays all-hits after warm-up."""
        self._input_shapes = dict(input_shapes)
        key = self._shape_key(self._input_shapes)
        cached = self._exec_cache.pop(key, None)
        if cached is not None:
            self._exec_cache[key] = cached  # re-insert as most recent
            self._exec = cached
            _telemetry.inc("predict.cache.hits")
        else:
            self._exec = self._sym.simple_bind(self._ctx, grad_req="null",
                                               **self._input_shapes)
            _telemetry.inc("predict.cache.misses")
            if self._cache_cap > 0:
                self._exec_cache[key] = self._exec
                while len(self._exec_cache) > self._cache_cap:
                    self._exec_cache.popitem(last=False)
                    _telemetry.inc("predict.cache.evictions")
        if _from_exec is not None:
            if _from_exec is not self._exec:
                self._transfer_state(_from_exec, self._exec)
            return
        arg_names = set(self._exec.arg_dict)
        aux_names = set(self._exec.aux_dict)
        for k, v in self._params.items():
            if k in self._input_shapes or k == "label" \
                    or k.endswith("_label"):
                continue
            if k in arg_names:
                self._exec.arg_dict[k][:] = v
            elif k in aux_names:
                self._exec.aux_dict[k][:] = v
        # label inputs are dead at inference (SoftmaxOutput passes data
        # through); anything else missing is a real error
        missing = [k for k in arg_names
                   if k not in self._params and k not in self._input_shapes
                   and not (k == "label" or k.endswith("_label"))]
        if missing and self._params:
            raise MXNetError("predictor: params blob is missing %s"
                             % sorted(missing))

    def _transfer_state(self, src, dst):
        """Carry weights/aux from executor ``src`` into ``dst`` by device
        buffer handoff — weight shapes are batch-independent, so this is
        reference-sharing, not a host round trip (the reference's
        MXPredReshape keeps the arg arrays for the same reason)."""
        for k, v in src.arg_dict.items():
            if self._is_weight(k, self._input_shapes) and k in dst.arg_dict:
                dst.arg_dict[k]._jx = v._jx
        for k, v in src.aux_dict.items():
            if k in dst.aux_dict:
                dst.aux_dict[k]._jx = v._jx

    # -- the C ABI surface -------------------------------------------------
    def set_input(self, key, data):
        """MXPredSetInput"""
        if key not in self._input_shapes:
            raise MXNetError("unknown input %r (have %s)"
                             % (key, sorted(self._input_shapes)))
        dst = self._exec.arg_dict[key]
        arr = np.asarray(data, np.float32)
        if arr.shape != tuple(dst.shape):
            # the C ABI hands inputs over as flat float buffers
            # (c_predict_api.h MXPredSetInput semantics)
            arr = arr.reshape(dst.shape)
        dst[:] = arr

    def forward(self):
        """MXPredForward"""
        self._exec.forward(is_train=False)

    def get_output_shape(self, index=0):
        """MXPredGetOutputShape"""
        return tuple(self._exec.outputs[index].shape)

    def get_output(self, index=0):
        """MXPredGetOutput — returns numpy (the C API copies out).

        Always an owning copy: ``asnumpy`` over a CPU jax buffer can be a
        zero-copy view, and the underlying executor buffer may be donated
        or reused by the next ``forward`` — a held output must not change
        retroactively when the predictor serves the next request."""
        out = self._exec.outputs[index].asnumpy()
        if not out.flags["OWNDATA"] or not out.flags["WRITEABLE"]:
            out = out.copy()
        return out

    def reshape(self, new_input_shapes):
        """MXPredReshape — rebind under the shape-keyed jit cache; params
        are retained (c_predict_api.cc keeps the arg arrays).  A shape
        seen within the last ``MXNET_PRED_CACHE_SIZE`` distinct shapes
        reuses its compiled executor (no retrace)."""
        shapes = dict(self._input_shapes)
        shapes.update(new_input_shapes)
        self._bind(shapes, _from_exec=self._exec)

    def free(self):
        """MXPredFree"""
        self._exec = None
        self._exec_cache.clear()


def create(symbol_json, param_blob, input_shapes, ctx=None):
    """Functional spelling of ``MXPredCreate``."""
    return Predictor(symbol_json, param_blob, input_shapes, ctx)
