"""Custom operators authored in Python.

Reference: ``python/mxnet/operator.py`` — ``CustomOp`` :396 / ``CustomOpProp``
:442 / ``register`` :576 (the modern path, dispatched through the C ``Custom``
op at ``src/operator/custom/custom.cc:183``), plus the legacy numpy callback
paths ``NumpyOp`` :126 (``_Native``, ``src/operator/native_op.cc``) and
``NDArrayOp`` :226 (``_NDArray``, ``src/operator/ndarray_op.cc``).

TPU-native design: the reference runs custom-op callbacks on an engine CPU
thread via C function pointers; here the callback is staged into the traced
XLA computation with ``jax.pure_callback`` (host callback with declared
result shapes), and the backward pass is wired through ``jax.custom_vjp`` so
``jax.grad`` through the whole fused graph calls the user's ``backward``.
Shape/type inference comes from the prop's ``infer_shape``/``infer_type``
exactly as the reference's ``CustomOpProp`` callbacks do.
"""

from __future__ import annotations

import numpy as np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop",
           "NumpyOp", "NDArrayOp", "PythonOp"]

_CUSTOM_PROPS: dict[str, type] = {}


class CustomOp:
    """Base class for stateful custom operators (ref ``operator.py:396``)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring grad_req (ref :420).

        ``src`` may be numpy or an NDArray (reference custom ops build
        ``mx.nd`` arrays host-side and assign them back).
        """
        import numpy as _np

        if not isinstance(src, _np.ndarray) and hasattr(src, "asnumpy"):
            src = src.asnumpy()
        if req in ("write", "inplace"):
            dst[...] = src
        elif req == "add":
            dst[...] += src
        elif req == "null":
            pass
        else:
            raise MXNetError("unknown req %r" % req)


class CustomOpProp:
    """Shape/type/IO metadata + operator factory (ref ``operator.py:442``)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        """-> (in_shapes, out_shapes, aux_shapes); default: all like in[0]."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), \
            [in_shape[0]] * len(self.list_auxiliary_states())

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad():
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Class decorator: register a ``CustomOpProp`` under ``op_type``
    (ref ``operator.py:576`` → ``MXCustomOpRegister``)."""

    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register %r: expected CustomOpProp subclass"
                             % reg_name)
        _CUSTOM_PROPS[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_prop(op_type):
    if op_type not in _CUSTOM_PROPS:
        raise MXNetError("custom op type %r not registered (use "
                         "mx.operator.register)" % op_type)
    return _CUSTOM_PROPS[op_type]


# ---------------------------------------------------------------------------
# Legacy numpy callback ops (ref ``operator.py:19-226``): PythonOp/NumpyOp/
# NDArrayOp.  Instances are process-local (like the reference's C function
# pointers — they do not survive symbol JSON round-trips) and dispatch through
# the same Custom machinery via a per-process instance table.
# ---------------------------------------------------------------------------

_LEGACY_TABLE: dict[int, "PythonOp"] = {}
_LEGACY_NEXT = [0]


class PythonOp:
    """Base for legacy numpy ops (ref ``operator.py:19``)."""

    def __init__(self, need_top_grad=True):
        self.info_ = None
        self.need_top_grad_ = need_top_grad
        _LEGACY_NEXT[0] += 1
        self._legacy_id = _LEGACY_NEXT[0]
        _LEGACY_TABLE[self._legacy_id] = self

    def __call__(self, *args, **kwargs):
        return self.get_symbol(*args, **kwargs)

    def get_symbol(self, *args, **kwargs):
        from . import symbol as sym

        kwargs["op_type"] = "_legacy"
        kwargs["legacy_id"] = self._legacy_id
        return sym.Custom(*args, **kwargs)

    # numpy-callback interface (ref :60-125)
    def forward(self, in_data, out_data):
        raise NotImplementedError

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_


class NumpyOp(PythonOp):
    """ref ``operator.py:126`` — callbacks receive numpy arrays."""


class NDArrayOp(PythonOp):
    """ref ``operator.py:226`` — same interface here (host arrays)."""


class _LegacyProp(CustomOpProp):
    """Adapts a PythonOp instance to the CustomOpProp interface."""

    def __init__(self, legacy_id):
        self._py_op = _LEGACY_TABLE[int(legacy_id)]
        super().__init__(need_top_grad=self._py_op.need_top_grad())

    def list_arguments(self):
        return list(self._py_op.list_arguments())

    def list_outputs(self):
        return list(self._py_op.list_outputs())

    def infer_shape(self, in_shape):
        ins, outs = self._py_op.infer_shape(in_shape)
        return ins, outs, []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        py_op = self._py_op

        class _Wrapped(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                py_op.forward(in_data=in_data, out_data=out_data)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                py_op.backward(out_grad=out_grad, in_data=in_data,
                               out_data=out_data, in_grad=in_grad)

        return _Wrapped()


_CUSTOM_PROPS["_legacy"] = _LegacyProp


def _make_prop(attrs):
    """Instantiate the prop for a Custom node's attrs (kwargs as strings,
    matching the reference's string-kwarg C protocol)."""
    kwargs = {k: v for k, v in attrs.items() if k != "op_type"}
    op_type = attrs.get("op_type")
    if not op_type:
        raise MXNetError("Custom op requires op_type attr")
    cls = get_prop(op_type)
    if cls is _LegacyProp:
        return cls(kwargs["legacy_id"])
    return cls(**kwargs)
