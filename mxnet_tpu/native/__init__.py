"""ctypes bindings for the native host runtime (src/native.cc).

The reference reaches its C++ runtime through a C ABI + ctypes
(``python/mxnet/base.py`` loads libmxnet.so; ``include/mxnet/c_api.h``).
Same shape here: ``src/native.cc`` is compiled once into
``libmxnet_tpu_native.so`` (lazy, cached) and loaded with ctypes — no
pybind11 dependency.

Exposes:
  * :class:`Engine` — host-side async var-dependency scheduler
    (``MXNET_ENGINE_TYPE=NaiveEngine`` selects synchronous dispatch, the
    reference's debugging story — SURVEY §5.2).
  * :class:`PooledStorage` — size-bucketed host buffer pool.
  * :func:`recordio_scan` — fast .rec boundary scan for .idx rebuilds.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
# repo checkout keeps the source at src/native.cc; installed wheels ship a
# copy inside the package (setup.py build_py copies it here)
_SRC_CANDIDATES = (
    os.path.join(os.path.dirname(os.path.dirname(_HERE)), "src",
                 "native.cc"),
    os.path.join(_HERE, "native.cc"),
)
_SRC = next((p for p in _SRC_CANDIDATES if os.path.exists(p)),
            _SRC_CANDIDATES[0])
_LIB_PATH = os.path.join(_HERE, "libmxnet_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()

_ENGINE_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def _build():
    # build to a per-pid temp path and rename atomically: concurrent
    # processes (SPMD workers) may race on the first build
    tmp = "%s.%d.tmp" % (_LIB_PATH, os.getpid())
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def get_lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        try:
            have_src = os.path.exists(_SRC)
            if not os.path.isfile(_LIB_PATH):
                _build()
            elif (have_src
                  and os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
                # stale .so next to a newer source; without a source, a
                # prebuilt .so is accepted as-is
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
        except (OSError, subprocess.CalledProcessError):
            return None
        lib.EngineCreate.restype = ctypes.c_void_p
        lib.EngineCreate.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.EngineFree.argtypes = [ctypes.c_void_p]
        lib.EngineNewVar.restype = ctypes.c_void_p
        lib.EngineNewVar.argtypes = [ctypes.c_void_p]
        lib.EnginePush.argtypes = [
            ctypes.c_void_p, _ENGINE_FN, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int]
        lib.EngineWaitForVar.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.EngineWaitForAll.argtypes = [ctypes.c_void_p]
        lib.StorageCreate.restype = ctypes.c_void_p
        lib.StorageFree.argtypes = [ctypes.c_void_p]
        lib.StorageAlloc.restype = ctypes.c_void_p
        lib.StorageAlloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.StorageRelease.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_size_t]
        lib.StorageDirectFree.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_size_t]
        lib.StorageReleaseAll.argtypes = [ctypes.c_void_p]
        lib.StorageUsedBytes.restype = ctypes.c_size_t
        lib.StorageUsedBytes.argtypes = [ctypes.c_void_p]
        lib.StoragePooledBytes.restype = ctypes.c_size_t
        lib.StoragePooledBytes.argtypes = [ctypes.c_void_p]
        lib.MXRecordIOScan.restype = ctypes.c_long
        lib.MXRecordIOScan.argtypes = [ctypes.c_char_p,
                                       ctypes.POINTER(ctypes.c_int64),
                                       ctypes.c_long]
        _lib = lib
        return _lib


class Engine:
    """Async host scheduler with read/write var dependencies.

    ``push(fn, const_vars, mutable_vars)`` — fn() runs on a worker thread
    once all prior writers of const_vars and all prior ops on mutable_vars
    finished; writers of a var are serialized, readers run concurrently.
    """

    def __init__(self, num_workers=None, engine_type=None):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        if engine_type is None:
            engine_type = os.environ.get("MXNET_ENGINE_TYPE",
                                         "ThreadedEngine")
        naive = 1 if engine_type == "NaiveEngine" else 0
        if num_workers is None:
            num_workers = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS",
                                             "4"))
        self._lib = lib
        self._h = lib.EngineCreate(num_workers, naive)
        # keep callbacks alive until executed
        self._cbs = {}
        self._cb_lock = threading.Lock()
        self._cb_id = 0

    def new_var(self):
        return self._lib.EngineNewVar(self._h)

    def push(self, fn, const_vars=(), mutable_vars=()):
        with self._cb_lock:
            self._cb_id += 1
            cid = self._cb_id

        def run(_ctx, _cid=cid, _fn=fn):
            try:
                _fn()
            finally:
                with self._cb_lock:
                    self._cbs.pop(_cid, None)

        cb = _ENGINE_FN(run)
        with self._cb_lock:
            self._cbs[cid] = cb
        nc, nm = len(const_vars), len(mutable_vars)
        carr = (ctypes.c_void_p * max(nc, 1))(*const_vars)
        marr = (ctypes.c_void_p * max(nm, 1))(*mutable_vars)
        self._lib.EnginePush(self._h, cb, None, carr, nc, marr, nm)

    def wait_for_var(self, var):
        self._lib.EngineWaitForVar(self._h, var)

    def wait_for_all(self):
        self._lib.EngineWaitForAll(self._h)

    def close(self):
        if self._h:
            self._lib.EngineWaitForAll(self._h)
            self._lib.EngineFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: broad-except — interpreter-shutdown GC
            pass


class PooledStorage:
    """Size-bucketed host memory pool (GPUPooledStorageManager analog)."""

    def __init__(self):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.StorageCreate()

    def alloc(self, size):
        p = self._lib.StorageAlloc(self._h, size)
        if not p:
            raise MemoryError("native alloc of %d bytes failed" % size)
        return p

    def free(self, ptr, size):
        """Return buffer to the pool for reuse."""
        self._lib.StorageRelease(self._h, ptr, size)

    def direct_free(self, ptr, size):
        self._lib.StorageDirectFree(self._h, ptr, size)

    def release_all(self):
        self._lib.StorageReleaseAll(self._h)

    @property
    def used_bytes(self):
        return self._lib.StorageUsedBytes(self._h)

    @property
    def pooled_bytes(self):
        return self._lib.StoragePooledBytes(self._h)

    def close(self):
        if self._h:
            self._lib.StorageFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: broad-except — interpreter-shutdown GC
            pass


def recordio_scan(path):
    """Return record start offsets of a .rec file (native scan); None if
    the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    # counting pass (offsets=NULL), then an exact-size offsets pass
    n = lib.MXRecordIOScan(path.encode(), None, 0)
    if n < 0:
        raise IOError("corrupt RecordIO file: %s" % path)
    if n == 0:
        return []
    arr = (ctypes.c_int64 * n)()
    n2 = lib.MXRecordIOScan(path.encode(), arr, n)
    if n2 != n:
        raise IOError("RecordIO file changed during scan: %s" % path)
    return list(arr)


_default_engine = None
_default_engine_lock = threading.Lock()


def default_engine():
    """Process-wide engine singleton (Engine::Get analog); None if the
    native toolchain is unavailable."""
    global _default_engine
    with _default_engine_lock:
        if _default_engine is None and get_lib() is not None:
            _default_engine = Engine()
        return _default_engine


# ---------------------------------------------------------------------------
# optional OpenCV-backed batch image decode (src/imgdecode.cc)
# ---------------------------------------------------------------------------
_IMG_SRC_CANDIDATES = (
    os.path.join(os.path.dirname(os.path.dirname(_HERE)), "src",
                 "imgdecode.cc"),
    os.path.join(_HERE, "imgdecode.cc"),
)
_IMG_SRC = next((p for p in _IMG_SRC_CANDIDATES if os.path.exists(p)),
                _IMG_SRC_CANDIDATES[0])
_IMG_LIB_PATH = os.path.join(_HERE, "libmxnet_tpu_imgdecode.so")

_img_lib = None
_img_lib_tried = False
_img_lib_lock = threading.Lock()


def _build_imgdecode():
    # flags via pkg-config when available, else the conventional paths
    try:
        flags = subprocess.run(
            ["pkg-config", "--cflags", "opencv4"], check=True,
            capture_output=True, text=True).stdout.split()
    except (OSError, subprocess.CalledProcessError):
        flags = ["-I/usr/include/opencv4"]
    libs = ["-lopencv_imgcodecs", "-lopencv_imgproc", "-lopencv_core"]
    tmp = "%s.%d.tmp" % (_IMG_LIB_PATH, os.getpid())
    cmd = (["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
            _IMG_SRC, "-o", tmp] + flags + libs)
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _IMG_LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def get_imgdecode_lib():
    """Load (building if needed) the OpenCV batch-decode library; None
    when OpenCV dev files are absent (callers use the Python path)."""
    global _img_lib, _img_lib_tried
    with _img_lib_lock:
        if _img_lib is not None or _img_lib_tried:
            return _img_lib
        _img_lib_tried = True
        try:
            have_src = os.path.exists(_IMG_SRC)
            if not os.path.isfile(_IMG_LIB_PATH):
                _build_imgdecode()
            elif (have_src and os.path.getmtime(_IMG_LIB_PATH)
                  < os.path.getmtime(_IMG_SRC)):
                _build_imgdecode()
            lib = ctypes.CDLL(_IMG_LIB_PATH)
        except (OSError, subprocess.CalledProcessError):
            return None
        lib.MXIMGBatchDecode.restype = ctypes.c_int
        lib.MXIMGBatchDecode.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),    # bufs
            ctypes.POINTER(ctypes.c_int64),     # lens
            ctypes.c_int,                       # n
            ctypes.c_int,                       # resize_shorter
            ctypes.POINTER(ctypes.c_float),     # crop_fx
            ctypes.POINTER(ctypes.c_float),     # crop_fy
            ctypes.POINTER(ctypes.c_ubyte),     # mirror
            ctypes.c_int, ctypes.c_int,         # out_h, out_w
            ctypes.c_void_p,                    # out (u8 HWC | f32 NCHW)
            ctypes.c_int,                       # out_f32_nchw
            ctypes.POINTER(ctypes.c_float),     # mean3 (nullable)
            ctypes.POINTER(ctypes.c_float),     # std3 (nullable)
            ctypes.c_float,                     # scale
            ctypes.c_int,                       # nthreads
        ]
        _img_lib = lib
        return _img_lib


def imgdecode_batch(lib, raw_bufs, out, resize_shorter, crop_fx, crop_fy,
                    mirror, out_h, out_w, norm=None, nthreads=1):
    """The one marshalling site for ``MXIMGBatchDecode``.

    ``raw_bufs``: list of JPEG byte strings; ``out``: preallocated numpy
    array — uint8 (N,H,W,3) or, with ``norm=(mean3, std3, scale)``,
    float32 (N,3,H,W) filled normalized; ``crop_fx/crop_fy``: per-image
    crop anchors in [0,1] or -1 for center; ``mirror``: per-image 0/1.
    Returns the number of images that failed to decode.
    """
    import ctypes as ct

    n = len(raw_bufs)
    bufs = (ct.c_void_p * n)(*[
        ct.cast(ct.c_char_p(b), ct.c_void_p) for b in raw_bufs])
    lens = (ct.c_int64 * n)(*[len(b) for b in raw_bufs])
    fx = (ct.c_float * n)(*crop_fx)
    fy = (ct.c_float * n)(*crop_fy)
    mir = (ct.c_ubyte * n)(*mirror)
    if norm is not None:
        mean3, std3, scale = norm
        mean_p = (ct.c_float * 3)(*mean3)
        std_p = (ct.c_float * 3)(*std3)
        f32 = 1
    else:
        mean_p = std_p = None
        scale, f32 = 1.0, 0
    return lib.MXIMGBatchDecode(
        bufs, lens, n, resize_shorter, fx, fy, mir, out_h, out_w,
        out.ctypes.data_as(ct.c_void_p), f32, mean_p, std_p,
        ct.c_float(scale), nthreads)
