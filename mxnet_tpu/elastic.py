"""Elastic distributed training — membership epochs + deterministic reshard.

PR 1 made the KVStore transport survive reconnects and *name* dead peers;
PR 5 made training resume bit-identically from batch-granular snapshots.
This module composes the two into elasticity (ROADMAP item 5, the
TensorFlow-paper checkpoint/restore-as-core-primitive design): world size
may change mid-job, and a membership change is a *replayable event*, not a
fatal one.

The three layers (docs/resilience.md "Elastic membership & resharding"):

* **Membership epochs** — the KVStore coordinator owns a monotonically
  increasing *membership epoch*.  Workers join via ``register``, leave via
  graceful ``deregister`` or heartbeat-death eviction; every change bumps
  the epoch.  All elastic push/pull/barrier traffic carries the sender's
  epoch, and straggler messages from the old world are rejected with a
  typed :class:`StaleEpoch` — never silently merged into the new world's
  sync rounds.
* **Deterministic resharding** — on an epoch bump every worker quiesces at
  its next batch boundary and runs the reshard cycle
  (:meth:`ElasticFitRun.sync`): all members of the new epoch rendezvous at
  the coordinator's quiesce barrier, rehydrate from the newest PR 5
  snapshot generation (params + server optimizer states + update counts +
  RNG + metric + data-ledger), push their :func:`assign_keys` share of the
  snapshot back to the server, and resume in-loop — the process never
  restarts, and two replays of the same elasticity schedule under the same
  ``MXNET_CHAOS_SEED`` produce bit-identical parameters because every
  input to the cycle (rollback generation, shard assignment, key
  ownership) is a pure function of on-disk state and ``(sorted ranks,
  epoch)``.
* **A checkpointable sharded data service** — :class:`mxnet_tpu.io.
  ElasticShardIter` assigns record shards per ``(rank, ranks, epoch)`` and
  carries a global sample-accounting ledger in the snapshot manifest, so
  a membership change neither skips nor repeats records (see io.py).

Env knobs (docs/how_to/env_var.md): ``MXNET_ELASTIC`` arms the layer,
``MXNET_ELASTIC_QUIESCE_DEADLINE`` bounds the reshard rendezvous,
``MXNET_ELASTIC_MIN_WORKERS`` / ``MXNET_ELASTIC_MAX_WORKERS`` bound the
world size.
"""

from __future__ import annotations

import os
import pickle

from . import faults as _faults
from . import telemetry as _telemetry
from . import tracing as _tracing
from .base import MXNetError

__all__ = ["StaleEpoch", "MembershipChanged", "enabled", "quiesce_deadline",
           "min_workers", "max_workers", "assign_keys", "shard_records",
           "ElasticFitRun"]


class StaleEpoch(MXNetError):
    """A push/pull/barrier/reshard message carried a membership epoch the
    coordinator has moved past: the sender belongs to the *old world* and
    must run the reshard cycle before touching the store again.  Typed —
    never silently merged — so sync rounds of the new world cannot be
    polluted by straggler traffic.  ``epoch`` is the coordinator's current
    membership epoch."""

    def __init__(self, msg, epoch=None):
        super().__init__(msg)
        self.epoch = epoch


class MembershipChanged(Exception):
    """Control-flow signal raised at a batch boundary by the elastic poll
    when the coordinator's membership epoch moved: ``fit(elastic=True)``
    catches it (and :class:`StaleEpoch`) and runs the reshard cycle.  Not
    an :class:`~mxnet_tpu.base.MXNetError` — it never escapes fit."""

    def __init__(self, old_epoch, new_epoch):
        super().__init__("membership epoch moved %s -> %s"
                         % (old_epoch, new_epoch))
        self.old_epoch = old_epoch
        self.new_epoch = new_epoch


# -- env knobs ---------------------------------------------------------------

def enabled():
    """True when ``MXNET_ELASTIC`` arms elastic membership."""
    return os.environ.get("MXNET_ELASTIC", "0") not in ("0", "", "false")


def quiesce_deadline():
    """Seconds the reshard rendezvous waits for all members before
    evicting non-arrivers (``MXNET_ELASTIC_QUIESCE_DEADLINE``)."""
    return float(os.environ.get("MXNET_ELASTIC_QUIESCE_DEADLINE", "30")
                 or 30)


def min_workers():
    """Floor on the elastic world size (``MXNET_ELASTIC_MIN_WORKERS``):
    membership below it fails reshard with a typed error, never a silent
    single-worker continuation."""
    return int(os.environ.get("MXNET_ELASTIC_MIN_WORKERS", "1") or 1)


def max_workers():
    """Ceiling on the elastic world size (``MXNET_ELASTIC_MAX_WORKERS``);
    0 = unlimited.  Registrations beyond it are rejected with a typed
    error."""
    return int(os.environ.get("MXNET_ELASTIC_MAX_WORKERS", "0") or 0)


# evicted-as-wedged re-registrations tolerated within ONE reshard cycle
# before the rank exits typed instead of thrashing the job through
# evict -> re-register -> epoch-bump forever
_MAX_REJOINS_PER_SYNC = 3


# -- pure reshard math -------------------------------------------------------

def assign_keys(keys, ranks, epoch):
    """Deterministic key -> owner-rank map: a pure function of
    ``(sorted keys, sorted ranks, epoch)``.  The owner of a key is the
    rank that pushes that key's snapshot value back to the coordinator
    during rehydration; rotating by ``epoch`` spreads the reload work
    across reshard events.  Every member computes the identical map."""
    ranks = sorted(ranks)
    if not ranks:
        raise MXNetError("assign_keys: empty rank set")
    return {k: ranks[(i + epoch) % len(ranks)]
            for i, k in enumerate(sorted(keys, key=str))}


def shard_records(record_ids, ranks, epoch):
    """Deterministic record partition: ``{rank: [ids...]}`` — a pure
    function of ``(sorted ids, sorted ranks, epoch)``.  Contiguous
    near-equal slices of the sorted id list, with the rank order rotated
    by ``epoch`` so repeated reshards move the boundary records around.
    Every member computes the identical partition; sizes differ by at
    most one record."""
    ranks = sorted(ranks)
    if not ranks:
        raise MXNetError("shard_records: empty rank set")
    ids = sorted(record_ids)
    w = len(ranks)
    rot = epoch % w
    order = ranks[rot:] + ranks[:rot]
    n = len(ids)
    bounds = [n * i // w for i in range(w + 1)]
    return {order[i]: ids[bounds[i]:bounds[i + 1]] for i in range(w)}


def _find_elastic_iter(it):
    """The :class:`~mxnet_tpu.io.ElasticShardIter` inside ``it`` (the
    iterator itself, or the SINGLE sub-iterator of a prefetch wrapper),
    or None.  A wrapper combining several sub-iterators never matches:
    the reshard protocol rewinds a wrapper onto exactly one inner state,
    so a composite wrapper trains with its data partition un-resharded
    (``ElasticFitRun.__init__`` warns about the degraded mode)."""
    from .io import ElasticShardIter, PrefetchingIter

    if isinstance(it, ElasticShardIter):
        return it
    if isinstance(it, PrefetchingIter) and len(it.iters) == 1 \
            and isinstance(it.iters[0], ElasticShardIter):
        return it.iters[0]
    return None


#: marker key under which an elastic leader snapshot carries the
#: coordinator-side optimizer updater states (pickled blob per server)
SERVER_STATES_KEY = "__elastic_server_states__"


class ElasticFitRun:
    """Per-``fit(elastic=True)`` reshard driver.

    Owns the batch-boundary membership poll, the data-ledger commit, the
    leader-only snapshot gating, and :meth:`sync` — the quiesce /
    rehydrate / reshard / resume cycle that keeps training in-loop across
    membership changes."""

    def __init__(self, module, kv, prefix, fit_data, logger):
        self.module = module
        self.kv = kv
        self.prefix = prefix
        self.logger = logger
        self.fit_data = fit_data
        self.data_iter = _find_elastic_iter(fit_data)
        self.ranks = None  # adopted at the first sync()
        if self.data_iter is None:
            logger.warning(
                "fit(elastic=True): train_data carries no singly-wrapped "
                "ElasticShardIter — membership changes will reshard "
                "parameters/optimizer state but NOT the data partition "
                "(records may be skipped or repeated across an "
                "elasticity event)")

    # -- batch-boundary hooks ---------------------------------------------
    def is_leader(self):
        """True when this rank is the membership leader (lowest live
        rank): the one rank that writes cadence snapshots and epoch
        checkpoints, so generations under the shared prefix never
        interleave between writers."""
        return self.ranks is None or self.kv.rank == min(self.ranks)

    def commit(self, data_batch):
        """Record the just-trained batch in the data ledger (non-pad
        records only).  Called after ``update()`` landed — a batch whose
        update was rejected with :class:`StaleEpoch` is never committed,
        so its records return to the remaining pool for the new world."""
        if self.data_iter is None or data_batch is None:
            return
        index = getattr(data_batch, "index", None)
        if index is not None:
            self.data_iter.commit(index, getattr(data_batch, "pad", 0) or 0)

    def poll(self, epoch, nbatch):
        """Membership poll at the batch boundary; raises
        :class:`MembershipChanged` when the coordinator's epoch moved.
        Passive: the coordinator stamps every elastic push/pull reply
        with its current epoch, so this batch's own traffic already
        carried the freshest observation and the poll costs no RPC
        (a bump landing after this batch's last reply is caught by the
        next batch's push raising :class:`StaleEpoch` — the update is
        aborted uncommitted, so exactly-once accounting holds either
        way).  The ``membership()`` RPC remains only as a fallback for
        the no-traffic-yet case.  The ``kvstore.membership`` fault point
        fires here: it severs this worker's transport — the observable
        state of a worker dying at a membership event."""
        if _faults.should_fire("kvstore.membership"):
            self.logger.warning(
                "fault 'kvstore.membership': severing transport at epoch "
                "%d batch %d (worker death at a membership boundary)",
                epoch, nbatch)
            self.kv._sever("fault 'kvstore.membership' killed this worker")
        server_epoch = getattr(self.kv, "observed_epoch", None)
        if server_epoch is None:
            server_epoch = self.kv.membership().get("epoch")
        if server_epoch is not None and server_epoch != self.kv.epoch:
            raise MembershipChanged(self.kv.epoch, server_epoch)

    def leave(self):
        """Graceful shrink on preemption: announce this worker's exit so
        the membership epoch bumps NOW and survivors quiesce at their
        next batch boundary — instead of blocking a full heartbeat
        deadline in a sync round the departed rank can never complete.
        Best-effort: a worker whose transport is already severed just
        falls back to heartbeat-death eviction."""
        try:
            self.kv.deregister()
        except Exception as e:  # noqa: broad-except — the worker is
            # exiting either way; eviction is the coordinator's fallback
            self.logger.warning(
                "elastic: graceful deregister failed (%s); survivors "
                "fall back to heartbeat-death eviction", e)

    def augment_snapshot(self, snap):
        """Fold the coordinator-side optimizer updater states into a
        leader snapshot, so rehydration restores the server's momentum
        exactly.  In update-on-kvstore mode the updater lives on the
        server and ``_capture_state_arrays`` sees none locally
        (``snap.opt_states`` is None here), so the marker dict replaces
        nothing; a NON-elastic resume of an elastic prefix recognizes
        the marker and skips the local install (module.py
        ``_restore_opt_snapshot``)."""
        try:
            blobs = self.kv.get_updater_states()
        except MXNetError:
            return  # no server-side optimizer (e.g. fit without one yet)
        snap.opt_states = {SERVER_STATES_KEY: blobs}

    # -- the reshard cycle -------------------------------------------------
    def sync(self, fallback):
        """Run the quiesce/rehydrate/reshard cycle until it lands on a
        stable membership epoch; returns ``(begin_epoch, resume_nbatch,
        resume_metric_state)`` for re-entering the batch loop.
        ``fallback`` is returned when no snapshot generation exists yet
        (a fresh job's initial sync).  A :class:`StaleEpoch` mid-cycle
        (membership moved again — e.g. a kill *during* the reshard)
        restarts the cycle; the ``elastic.reshard`` fault point fires at
        cycle entry to inject exactly that worker death."""
        rejoins = 0
        while True:
            if _faults.should_fire("elastic.reshard"):
                self.logger.warning(
                    "fault 'elastic.reshard': severing transport inside "
                    "the reshard cycle (worker death mid-reshard)")
                self.kv._sever("fault 'elastic.reshard' killed this worker "
                               "mid-reshard")
            # STACKED on this worker thread: the kvstore verbs the cycle
            # issues (reshard_sync/choice/commit, pulls) stamp this
            # span's context onto the wire, so the coordinator's
            # kvstore.* spans stitch into the same trace
            rsp = _tracing.start_span("elastic.reshard",
                                      rank=str(self.kv.rank),
                                      attempt=rejoins)
            try:
                out = self._cycle(fallback)
                rsp.end("ok")
                return out
            except StaleEpoch as e:
                rsp.end("retry", reason="stale_epoch")
                # if WE are the one who was evicted (slow past the
                # quiesce deadline while the socket stayed up), the
                # coordinator never re-admits a rank on its own — the
                # not-a-member reply would repeat forever.  Re-register
                # (the PR 1 same-rank rejoin; an elastic re-admission
                # bumps the epoch) before restarting the cycle.
                try:
                    member = self.kv.rank in (
                        self.kv.membership().get("ranks") or [])
                except MXNetError:
                    member = False
                if not member:
                    # bounded: a rank evicted as wedged EVERY cycle
                    # would otherwise thrash the whole job through
                    # evict -> re-register -> bump forever; after the
                    # cap it exits typed (survivors reshard without it)
                    # — resume-or-typed-error, never a livelock
                    rejoins += 1
                    if rejoins > _MAX_REJOINS_PER_SYNC:
                        raise MXNetError(
                            "elastic: this rank (%s) was evicted from "
                            "the membership %d times within one reshard "
                            "cycle (consistently slower than the "
                            "quiesce deadline); giving up instead of "
                            "thrashing the job — raise "
                            "MXNET_ELASTIC_QUIESCE_DEADLINE or fix the "
                            "slow rank" % (self.kv.rank, rejoins)) from e
                    self.logger.warning(
                        "elastic: this rank (%s) was evicted from the "
                        "membership; re-registering before the reshard "
                        "cycle restarts (attempt %d/%d)", self.kv.rank,
                        rejoins, _MAX_REJOINS_PER_SYNC)
                    self.kv.reconnect()
                self.logger.info(
                    "elastic: membership moved during the reshard cycle "
                    "(%s); restarting the cycle", e)
            except BaseException:
                # the span is STACKED: every exit must pop it or the
                # thread-local parent chain leaks into later spans
                rsp.end("error")
                raise

    def _cycle(self, fallback):
        kv, mod = self.kv, self.module
        rep = kv.reshard_sync()
        ranks, epoch = rep["ranks"], rep["epoch"]
        state = None
        if self.prefix is not None:
            state = self._adopt_generation(ranks)
        out = fallback
        if state is not None:
            # module rehydration: params + optimizer update counts + RNG
            # streams from the adopted generation (the PR 5 resume path,
            # driven mid-fit instead of at process start)
            mod.set_params(state.arg_params, state.aux_params,
                           force_init=True)
            if hasattr(mod, "_restore_opt_snapshot"):
                mod._restore_opt_snapshot(None, state.opt_counts)
            rng = state.rng_state or {}
            if rng.get("global"):
                from . import random as _random

                _random.set_state(rng["global"])
            ex = getattr(mod, "_exec", None)
            if ex is not None and rng.get("exec_step") is not None:
                ex._rng_step = int(rng["exec_step"])
            out = (state.epoch,
                   state.nbatch if state.nbatch is not None else None,
                   state.metric_state)
            # coordinator rehydration: each key's assign_keys owner
            # pushes its snapshot value back (version/round bookkeeping
            # reset server-side), so survivors and newcomers alike pull
            # one authoritative post-reshard state
            entries = mod._elastic_param_entries() \
                if hasattr(mod, "_elastic_param_entries") else []
            if entries:
                owners = assign_keys([i for i, _n in entries], ranks, epoch)
                for i, name in entries:
                    if owners[i] == kv.rank and name in state.arg_params:
                        kv.reload(i, state.arg_params[name].asnumpy())
        if kv.rank == min(ranks):
            self._reinstall_optimizer(state, len(ranks))
        # rendezvous: every member's reloads (and the leader's optimizer
        # reinstall) are visible before ANY member trains or pulls
        kv.reshard_commit()
        self._reshard_data(state, ranks, epoch)
        if state is not None and hasattr(mod, "_elastic_pull_params"):
            mod._elastic_pull_params()
        initial = self.ranks is None
        self.ranks = list(ranks)
        if not initial:
            # the initial rendezvous is job assembly, not an elasticity
            # event: dashboards keyed on resharded.count must read zero
            # for a run with no membership change after assembly
            _telemetry.inc("elastic.resharded.count")
            _telemetry.event("elastic.reshard", epoch=epoch,
                             ranks=list(ranks), rank=kv.rank,
                             rollback=None if state is None else
                             [state.epoch, state.nbatch])
        self.logger.info(
            "elastic: resharded onto membership epoch %d (ranks %s)%s",
            epoch, list(ranks),
            "" if state is None else " from snapshot epoch %s batch %s"
            % (state.epoch, state.nbatch))
        return out

    def _adopt_generation(self, ranks):
        """ONE rollback generation for the whole world: the leader reads
        the manifest, picks the newest verified generation (or None) and
        announces it through the coordinator (``reshard_choice``); every
        follower blocks for the announcement and loads EXACTLY that
        generation.  Independent manifest reads could disagree — a
        straggler ex-leader's inline write landing between two members'
        reads, shared-FS visibility lag, a per-member sha fallback — and
        members adopting different generations would reload mixed server
        parameters and diverge their data ledgers.  A follower that
        cannot load the announced generation retries briefly (FS lag),
        then dies on a typed error rather than training diverged."""
        import time as _time

        from .checkpoint import load_latest_state

        kv = self.kv
        if kv.rank == min(ranks):
            state = load_latest_state(self.prefix, logger=self.logger)
            kv.set_reshard_choice(
                None if state is None else
                {"epoch": state.epoch, "nbatch": state.nbatch})
            return state
        want = kv.get_reshard_choice()["choice"]
        if want is None:
            return None
        key = (want["epoch"], want["nbatch"])
        for attempt in range(3):
            if attempt:
                _time.sleep(0.2)  # shared-FS visibility lag
            state = load_latest_state(self.prefix, logger=self.logger,
                                      want=key)
            if state is not None:
                return state
        raise MXNetError(
            "elastic reshard: the leader adopted snapshot generation "
            "(epoch %s, nbatch %s) but this member cannot load/verify "
            "it under prefix %r — refusing to train diverged"
            % (want["epoch"], want["nbatch"], self.prefix))

    def _reinstall_optimizer(self, state, world):
        """Leader half of rehydration: re-command the server optimizer
        with the gradient scale of the NEW world size, then restore its
        updater states from the adopted snapshot (``set_optimizer``
        creates a fresh updater, so states are re-installed after)."""
        mod = self.module
        opt = getattr(mod, "_optimizer", None)
        if opt is None:
            return
        shapes = getattr(mod, "_data_shapes", None)
        rescaled = False
        if shapes and getattr(mod, "_auto_rescale_grad", False):
            # framework-derived rescale follows the world size; a
            # user-supplied rescale_grad is honored across reshards the
            # same way init_optimizer honors it at launch
            want = 1.0 / (shapes[0][1][0] * world)
            rescaled = opt.rescale_grad != want
            opt.rescale_grad = want
        if state is None:
            # no snapshot (initial rendezvous, or a bump before the
            # leader's first write): the scale still needs re-commanding
            # when the adopted world differs from the one init_optimizer
            # derived for — e.g. an over-subscribed initial cohort that
            # admitted more arrivers than the launch num_workers.  The
            # server's updater states are carried across untouched
            # (set_optimizer builds a fresh updater).
            if rescaled:
                try:
                    blobs = self.kv.get_updater_states()
                except MXNetError:
                    blobs = None
                self.kv.set_optimizer(opt)
                if blobs:
                    self.kv.set_updater_states(blobs)
            return
        self.kv.set_optimizer(opt)
        blobs = None
        if state.states_bytes:
            try:
                payload = pickle.loads(state.states_bytes)
            except Exception:  # noqa: broad-except — a non-elastic
                # .states payload (raw updater tree) is not restorable
                # onto the server; momentum restarts from zero
                payload = None
            if isinstance(payload, dict):
                blobs = payload.get(SERVER_STATES_KEY)
        elif getattr(state, "states_path", None) \
                and getattr(mod, "_update_on_kvstore", False):
            # an adopted epoch-boundary checkpoint: its .states file IS
            # the coordinator capture (kvstore.save_optimizer_states
            # wire format), not a snapshot's marker pickle — recover the
            # blobs from disk instead of zeroing the server's momentum
            from .kvstore import states_file_blobs

            try:
                with open(state.states_path, "rb") as f:
                    blobs = states_file_blobs(f.read())
            except (OSError, pickle.UnpicklingError) as e:
                self.logger.warning(
                    "elastic: adopted checkpoint optimizer states %s "
                    "unreadable (%s)", state.states_path, e)
        if blobs:
            self.kv.set_updater_states(blobs)
        else:
            self.logger.warning(
                "elastic: adopted snapshot carries no coordinator "
                "optimizer states; server momentum restarts from zero")

    def _reshard_data(self, state, ranks, epoch):
        """Data-service half: adopt the snapshot's global ledger, then
        recompute this rank's shard of the REMAINING records for the new
        membership.  A prefetch wrapper is drained and re-armed through
        the PR 5 pre-produce state protocol so its buffered batch never
        leaks across the reshard."""
        it = self.data_iter
        if it is None:
            return
        from .io import PrefetchingIter

        wrapper = self.fit_data \
            if self.fit_data is not it \
            and isinstance(self.fit_data, PrefetchingIter) else None
        if wrapper is not None:
            # park the producer threads BEFORE touching the inner
            # iterator: a produce racing the reshard could advance the
            # post-reshard cursor before state_dict() below captures it,
            # silently skipping the new assignment's first batch
            wrapper.drain()
        ledger_state = None
        if state is not None and state.iter_state is not None:
            st = state.iter_state
            if isinstance(st, dict) and st.get("type") in (
                    "PrefetchingIter", "DevicePrefetchIter"):
                inner = st.get("inner") or []
                st = inner[0] if len(inner) == 1 else None
            if isinstance(st, dict) and st.get("type") == "ElasticShardIter":
                ledger_state = st
        it.reshard(self.kv.rank, ranks, epoch, state=ledger_state)
        if wrapper is not None:
            # drain-then-reshard: rewind the wrapper onto the inner
            # iterator's post-reshard state and re-arm the producers
            wrapper.load_state_dict(
                {"type": type(wrapper).__name__,
                 "inner": [it.state_dict()]})
