"""KVStore — the gradient-exchange API (``mx.kv``).

Reference: ``include/mxnet/kvstore.h`` + ``src/kvstore/`` (SURVEY §2.4):
``create(type)``, int/str keys, ``init/push/pull`` with per-key aggregation,
``set_optimizer`` (updater applied where the weights live), rank/num_workers,
barrier, server command protocol.

TPU-native mapping (SURVEY §5.8): there is no parameter server —

* ``local`` / ``device``: single-process aggregation.  Pushed gradient lists
  are summed on device (the ``CommDevice`` analog; on a TPU mesh the sum is
  an XLA ``psum`` compiled into the step — see ``parallel/``), and the
  updater runs on the stored copy.
* ``dist_sync`` / ``dist_async``: multi-process parameter server
  (``kvstore_server.py`` — the ``KVStoreDist``/``KVStoreDistServer`` pair,
  ``src/kvstore/kvstore_dist.h``), wired by the same ``DMLC_*`` env
  protocol and ``tools/launch.py``.  Sync mode gives the reference's
  per-key merge-round barrier + server-side optimizer; on TPU pods the
  gradient plane should instead be in-graph DCN collectives (``parallel/``)
  — the PS covers the update-on-server semantics collectives can't express.

The API surface (push/pull ordering per key, update-on-kvstore semantics) is
preserved so ``Module``/``model.py`` code from the reference runs unchanged.
"""

from __future__ import annotations

import os
import pickle
import time as _time
import zlib as _zlib

from . import elastic as _elastic
from . import faults as _faults
from . import telemetry as _telemetry
from . import tracing as _tracing
from .base import MXNetError, atomic_write_bytes as _atomic_write_bytes
from .elastic import StaleEpoch
from .ndarray import NDArray, zeros
from .retry import RetryPolicy, retry_call


#: magic prefix of a multi-server optimizer-states file
#: (``save_optimizer_states`` / ``load_optimizer_states`` wire format)
MULTI_STATES_MAGIC = b"MXPSMULTI"


def states_file_blobs(data):
    """Decode a ``save_optimizer_states`` file payload into the per-shard
    coordinator blob list (single raw blob, or the multi-server
    ``MULTI_STATES_MAGIC`` + pickled list)."""
    if data.startswith(MULTI_STATES_MAGIC):
        return pickle.loads(data[len(MULTI_STATES_MAGIC):])
    return data


def _nd_nbytes(arr):
    """Byte size of an NDArray/ndarray for the transport byte counters."""
    import numpy as _np

    try:
        return int(arr.size) * _np.dtype(arr.dtype).itemsize
    except TypeError:
        return 0

__all__ = ["KVStore", "KVStoreDist", "ConnectionLost", "StaleEpoch",
           "create"]


class ConnectionLost(MXNetError):
    """The PS transport died under an RPC (peer FIN/RST, NIC loss, armed
    ``kvstore.push.socket`` fault).  The server's per-key state survives a
    worker-side transport loss, so ``KVStoreDist.reconnect()`` can rejoin
    with the same rank and resume."""


def _ctype_key_value(keys, vals):
    """Normalize to (list[key], list[list[NDArray]]) — reference kvstore.py."""
    if isinstance(keys, (int, str)):
        keys = [keys]
        vals = [vals]
    out_vals = []
    for v in vals:
        if isinstance(v, NDArray):
            out_vals.append([v])
        else:
            out_vals.append(list(v))
    return list(keys), out_vals


def _merge_devices(vlist):
    """Sum a pushed per-device NDArray list onto the first device (the
    CommDevice reduce, ``src/kvstore/comm.h:200``)."""
    merged = vlist[0]
    for v in vlist[1:]:
        merged = merged + v.as_in_context(merged.context)
    return merged


class KVStore:
    """reference ``python/mxnet/kvstore.py:35``"""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None

    # -- properties -------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """reference kvstore.py rank — process index."""
        return 0

    @property
    def num_workers(self):
        return 1

    # -- data plane -------------------------------------------------------
    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            self._store[k] = vlist[0].copy()

    def push(self, key, value, priority=0):
        """Aggregate pushed values per key; apply updater if set (the
        reference's server-side/updater-side optimizer application,
        ``kvstore_local.h:49-60``)."""
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            merged = _merge_devices(vlist)
            if _telemetry.enabled():
                _telemetry.inc("kvstore.push.count", store=self._type)
                _telemetry.inc("kvstore.push.bytes", _nd_nbytes(merged),
                               store=self._type)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                # default updater is ASSIGN (reference kvstore_local.h: the
                # merged reduce replaces the stored value; aggregation is
                # across the pushed device list, not across pushes)
                merged.copyto(self._store[k])

    def pull(self, key, out=None, priority=0):
        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            if _telemetry.enabled():
                _telemetry.inc("kvstore.pull.count", store=self._type)
                _telemetry.inc("kvstore.pull.bytes",
                               _nd_nbytes(self._store[k]) * len(olist),
                               store=self._type)
            for o in olist:
                self._store[k].copyto(o)

    # -- updater / optimizer ----------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """reference kvstore.py:232 — on dist the optimizer is serialized to
        servers; here the updater always runs where the weights live."""
        from .optimizer import get_updater

        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    # -- control plane ----------------------------------------------------
    def barrier(self):
        pass

    def send_command_to_servers(self, head, body):
        """No servers exist; kept for API parity (logged no-op)."""

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not initialized on kvstore")
        states = self._updater.get_states()
        _atomic_write_bytes(fname, states)

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not initialized on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


class KVStoreDist(KVStore):
    """Parameter-server worker (reference ``src/kvstore/kvstore_dist.h``).

    Connects to the ``kvstore_server`` over TCP using the reference's env
    wire protocol (``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``).  Per-key
    push/pull ordering is version-gated: each sync push returns the round
    it lands in and subsequent pulls block server-side until that round is
    applied — the recv-buffer var-dependency of ``kvstore_dist.h:93-121``
    expressed as versions instead of engine vars.
    """

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        from . import kvstore_server as ps

        self._ps = ps
        self._host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        self._port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9090"))
        # multi-server sharding (reference ps-lite: N servers, big arrays
        # split by EncodeKey, kvstore_dist.h:40): server i at port+i;
        # server 0 doubles as the scheduler (ranks, barrier)
        self._num_servers = max(1, int(os.environ.get("DMLC_NUM_SERVER",
                                                      "1")))
        self._bigarray_bound = int(os.environ.get(
            "MXNET_KVSTORE_BIGARRAY_BOUND", "1000000"))
        self._socks = []
        self._sock = None
        self._rank = None
        self._versions = {}
        # per-(sub)key count of this rank's acked pushes — the "round"
        # field of a push.  Distinct from _versions (server's global
        # version, which gates pulls): in sync mode the two coincide, but
        # in async the version advances once per push from ANY rank, so
        # only this counter lines up with the server's per-rank replay
        # window (st.pushed[rank] / round_base)
        self._push_seq = {}
        # (sub)keys whose push RPC was acked before a later key in the
        # same push() call lost the transport: their server-side round
        # already counted, and their ack advanced self._push_seq past the
        # server's replay window — so the documented recovery (reconnect()
        # + re-push the same batch) must skip them client-side or their
        # gradient lands twice.  Consumed only by the first push after
        # reconnect() (_repush_window), so an application that abandons
        # the failed batch instead cannot silently lose fresh gradients.
        self._acked_in_failed_push = set()
        self._repush_window = False
        worker_id = os.environ.get("DMLC_WORKER_ID")
        if worker_id is None and os.environ.get("DMLC_ROLE") == "worker":
            # under an MPI/slurm *launcher* every rank shares one env; the
            # process-manager rank is the worker identity (dmlc-tracker's
            # mpi backend relies on the same variables).  Gated on DMLC_ROLE
            # so a process merely running inside a slurm/MPI allocation does
            # not silently adopt that rank and collide on rejoin.
            for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_PROCID"):
                if var in os.environ:
                    worker_id = os.environ[var]
                    break
        self._preferred_rank = int(worker_id) if worker_id is not None \
            else None
        # elastic membership (docs/resilience.md "Elastic membership"):
        # after the first reshard_sync adoption every push/pull/barrier
        # carries this worker's membership epoch, so straggler traffic
        # from an old world is rejected with a typed StaleEpoch.  None
        # until adopted — the init/first-pull phase predates membership
        # stabilization and is epoch-free by design.
        self._elastic = _elastic.enabled()
        self._epoch = None
        # the most recent membership epoch observed on any server reply
        # (elastic servers stamp push/pull success replies), giving the
        # batch-boundary poll a passive signal instead of a dedicated RPC
        self._observed_epoch = None
        if self._elastic and self._num_servers > 1:
            raise MXNetError(
                "MXNET_ELASTIC=1 requires a single kvstore server "
                "(DMLC_NUM_SERVER=1): membership epochs live on the "
                "coordinator, and shard servers evict dead peers "
                "independently, so their epochs would diverge and "
                "permanently reject each other's traffic as stale "
                "(docs/resilience.md 'Elastic membership & resharding')")
        self._connect_and_register()
        # TPU-native gradient plane: join the jax.distributed process
        # group so training steps run in-graph collectives across
        # processes (psum over the global mesh) instead of per-step PS
        # push/pull.  dist_async keeps the PS plane — asynchronous
        # updates have no collective analog (SURVEY §5.8).
        self.in_graph_sync = False
        if "_async" not in kv_type:
            from . import dist as _dist

            self.in_graph_sync = _dist.init_from_env(rank_hint=self._rank)

    # -- transport --------------------------------------------------------
    @staticmethod
    def _connect_policy():
        """Backoff/deadline for connect+register, shared by initial
        connection and ``reconnect()``.  ``MXNET_KVSTORE_CONNECT_DEADLINE``
        (seconds) bounds the whole sequence; the legacy
        ``MXNET_KVSTORE_CONNECT_TIMEOUT`` spelling is honored as a
        fallback, and ``MXNET_RETRY_TOTAL_DEADLINE`` caps the cumulative
        cross-attempt wall clock on top (RetryPolicy applies it) so a
        flapping server can never compound the backoff into an unbounded
        connect stall."""
        deadline = float(os.environ.get(
            "MXNET_KVSTORE_CONNECT_DEADLINE",
            os.environ.get("MXNET_KVSTORE_CONNECT_TIMEOUT", "120")))
        return RetryPolicy(deadline_s=deadline, base_delay=0.2,
                           max_delay=2.0, jitter=0.5)

    def _close_socks(self):
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        self._socks = []
        self._sock = None

    def _connect_all(self, policy, start):
        """(Re)open one socket per server; servers import jax before
        binding, so each connect retries with backoff until the shared
        deadline."""
        import socket as _socket

        self._close_socks()
        socks = []
        for sid in range(self._num_servers):
            socks.append(retry_call(
                lambda sid=sid: _socket.create_connection(
                    (self._host, self._port + sid), timeout=300),
                retry_on=(OSError,), policy=policy, start=start,
                metric="kvstore.connect"))
        self._socks = socks
        self._sock = socks[0]  # scheduler

    def _reopen_sock(self, sid):
        """Best-effort reopen of one server connection (retry hook).  A
        failed connect leaves the old dead socket in place, so the next
        RPC attempt fails fast and the caller's retry loop comes back
        here until its deadline expires."""
        import socket as _socket

        try:
            self._socks[sid].close()
        except OSError:
            pass
        try:
            self._socks[sid] = _socket.create_connection(
                (self._host, self._port + sid), timeout=300)
        except OSError:
            return
        if sid == 0:
            self._sock = self._socks[0]

    def _connect_and_register(self, rejoin=False):
        policy = self._connect_policy()
        start = _time.monotonic()
        self._connect_all(policy, start)
        # rejoin=True marks a same-process reconnect(): per-key round
        # numbering (self._push_seq) is continuous, so the server may
        # treat a low-numbered re-push as a replay and dedup it; a fresh
        # process restarts numbering at 0 and must not be deduped
        reg = {"cmd": "register", "role": "worker", "rejoin": rejoin}
        if self._preferred_rank is not None:
            # announce identity so a restarted worker rejoins with its old
            # rank (the reference's ps-lite is_recovery path)
            reg["preferred_rank"] = self._preferred_rank

        # a loaded host can drop the just-accepted connection before the
        # register reply (seen as a suite-level flake) — as a clean FIN
        # (ConnectionLost) or an RST (OSError).  Retrying is only safe
        # when the registration is idempotent server-side, i.e. when
        # preferred_rank identifies this worker (the rejoin path); without
        # an identity a processed-but-unacknowledged register would leak a
        # ghost rank on retry, so that case still raises.
        def _register_retryable(e):
            dropped = isinstance(e, (ConnectionLost, OSError))
            return dropped and "preferred_rank" in reg

        reply = retry_call(
            lambda: self._rpc(reg),
            retry_on=(MXNetError, OSError),
            retry_if=_register_retryable,
            on_retry=lambda e, n: self._connect_all(policy, start),
            policy=policy, start=start, metric="kvstore.register")
        self._rank = reply["rank"]
        self._num_workers = reply["num_workers"]
        self.is_recovery = bool(reply.get("is_recovery", False))
        self._update_on_kvstore = True
        # announce the scheduler-assigned rank to every shard server: each
        # server keeps its own live/round_base bookkeeping, so without
        # this a restarted worker's fresh round numbering would be misread
        # as replays on servers 1..N-1 (its pushes silently dropped), and
        # their dead-peer detection would never know the rank existed.
        # preferred_rank makes the announce idempotent, so a dropped
        # connection mid-announce is safely retried on a fresh socket.
        ann = {"cmd": "register", "role": "worker", "rejoin": rejoin,
               "preferred_rank": self._rank}
        for sid in range(1, len(self._socks)):
            retry_call(
                lambda sid=sid: self._rpc(ann, sock=self._socks[sid]),
                retry_on=(MXNetError, OSError),
                # a dropped connection is retryable; a server error reply
                # (e.g. a rank collision) is permanent — fail fast rather
                # than burning the whole connect deadline on it
                retry_if=lambda e: isinstance(e, (ConnectionLost, OSError)),
                on_retry=lambda e, n, sid=sid: self._reopen_sock(sid),
                policy=policy, start=start, metric="kvstore.announce")
        # command every server into the mode this type implies (reference
        # kvstore.cc:32-35: sync unless the type carries _async)
        for s in self._socks:
            self._rpc({"cmd": "sync_mode",
                       "value": "_async" not in self._type}, sock=s)

    def reconnect(self):
        """Rebuild the transport after a :class:`ConnectionLost`.

        Re-registers with the current rank (the server's is_recovery
        path), so per-key versions and server-side optimizer state are
        resumed, not reset.  Bounded by the same connect deadline as the
        initial connection."""
        if self._rank is not None:
            self._preferred_rank = self._rank
        self._connect_and_register(rejoin=True)
        _telemetry.inc("kvstore.reconnects")
        _telemetry.event("kvstore.reconnect", rank=self._rank)
        # the next push() is the documented re-push of the batch that lost
        # its transport: let it skip the keys that were already acked
        self._repush_window = True

    def _rpc(self, msg, sock=None):
        sock = self._sock if sock is None else sock
        try:
            self._ps.send_msg(sock, msg)
            reply = self._ps.recv_msg(sock)
        except OSError as e:
            _telemetry.inc("kvstore.connection_lost", cmd=msg.get("cmd"))
            raise ConnectionLost(
                "kvstore transport failure during %r: %s "
                "(reconnect() rejoins with the same rank)"
                % (msg.get("cmd"), e))
        if reply is None:
            _telemetry.inc("kvstore.connection_lost", cmd=msg.get("cmd"))
            raise ConnectionLost(
                "kvstore server connection lost during %r "
                "(reconnect() rejoins with the same rank)"
                % (msg.get("cmd"),))
        if "error" in reply:
            if reply.get("stale_epoch"):
                # typed: the coordinator moved to a new membership epoch
                # — the caller must run the reshard cycle, not retry
                raise StaleEpoch(reply["error"], epoch=reply.get("epoch"))
            raise MXNetError(reply["error"])
        if self._elastic and "epoch" in reply:
            self._observed_epoch = reply["epoch"]
        return reply

    def _with_epoch(self, msg):
        """Stamp elastic traffic with this worker's adopted membership
        epoch (no-op before adoption / outside elastic mode)."""
        if self._elastic and self._epoch is not None:
            msg["epoch"] = self._epoch
        return msg

    @staticmethod
    def _with_trace(msg):
        """Stamp an outgoing verb with the calling thread's span
        context (``{"trace_id", "span_id"}``) so the server's dispatch
        span parents on this worker's current span (the fit batch, a
        reshard cycle) and worker↔coordinator spans stitch into one
        tree.  One boolean check when tracing is off — the non-traced
        push/pull hot path pays no clock read or allocation."""
        if _tracing.enabled():
            c = _tracing.ctx()
            if c is not None:
                msg["trace"] = c
        return msg

    def _sever(self, why):
        """Close every server socket and raise :class:`ConnectionLost` —
        the observable state of this worker dying abruptly.  Used by the
        ``kvstore.membership`` / ``elastic.reshard`` fault points (and
        chaos tests) to kill a worker at a deterministic point."""
        self._close_socks()
        raise ConnectionLost(why)

    def _server_of(self, key):
        """Small keys live whole on one server (round-robin by key).
        String keys route by crc32, NOT builtin ``hash()``: with
        per-process ``PYTHONHASHSEED``, ``hash(str)`` differs across
        worker processes, so two workers would push the same key to
        DIFFERENT servers and the merge round would never complete
        (found by the replica-divergence lint pass)."""
        try:
            return int(key) % self._num_servers
        except (TypeError, ValueError):
            return _zlib.crc32(str(key).encode("utf-8")) \
                % self._num_servers

    def _shards(self, key, size):
        """[(subkey, server, slice)] — arrays over the bigarray bound
        split into one contiguous chunk per server (EncodeKey analog)."""
        n = self._num_servers
        if n == 1 or size < self._bigarray_bound:
            return None
        bounds = [size * i // n for i in range(n + 1)]
        return [("%s#%d" % (key, i), i, slice(bounds[i], bounds[i + 1]))
                for i in range(n) if bounds[i + 1] > bounds[i]]

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            # first init wins on the server (rank-0 broadcast semantics,
            # kvstore_dist.h:58-76)
            v = vlist[0].asnumpy()
            shards = self._shards(k, v.size)
            if shards is None:
                self._rpc({"cmd": "init", "key": k, "value": v},
                          sock=self._socks[self._server_of(k)])
            else:
                flat = v.reshape(-1)
                for sk, sid, sl in shards:
                    self._rpc({"cmd": "init", "key": sk,
                               "value": flat[sl]}, sock=self._socks[sid])
        if not self._elastic:
            # elastic jobs synchronize at the reshard rendezvous instead:
            # a barrier here would wedge a mid-job joiner against
            # survivors that are deep in the batch loop
            self.barrier()

    def push(self, key, value, priority=0):
        """Push gradients; on :class:`ConnectionLost` the documented
        recovery is ``reconnect()`` then re-calling ``push`` with the
        SAME keys/values — keys the failed call already got acked are
        skipped client-side (their round counted server-side), and unacked
        keys re-send their original round so the server's replay guard
        dedups a push whose reply (not the push itself) was lost."""
        if _faults.should_fire("kvstore.push.socket"):
            # sever the transport before the send — the observable state
            # of a peer/NIC dying mid-push.  The next RPC fails with a
            # clean ConnectionLost; the server never saw the push, so a
            # reconnect()+re-push lands in the correct sync round.
            for s in self._socks:
                try:
                    s.close()
                except OSError:
                    pass
        keys, vals = _ctype_key_value(key, value)
        already_acked = self._acked_in_failed_push \
            if self._repush_window else set()
        self._repush_window = False
        self._acked_in_failed_push = set()
        acked = set()

        def _push_one(k, value, sock):
            if k in already_acked:
                acked.add(k)  # counted in the call that lost its transport
                return
            tele = _telemetry.enabled()
            t0 = _time.perf_counter() if tele else 0.0
            try:
                reply = self._rpc(self._with_trace(self._with_epoch(
                    {"cmd": "push", "key": k, "value": value,
                     "rank": self._rank,
                     "round": self._push_seq.get(k, 0)})), sock=sock)
            except (ConnectionLost, OSError):
                self._acked_in_failed_push = acked
                raise
            if tele:
                _telemetry.observe("kvstore.push.seconds",
                                   _time.perf_counter() - t0,
                                   store=self._type)
                _telemetry.inc("kvstore.push.count", store=self._type)
                _telemetry.inc("kvstore.push.bytes", int(value.nbytes),
                               store=self._type)
            self._push_seq[k] = self._push_seq.get(k, 0) + 1
            self._versions[k] = max(self._versions.get(k, 0),
                                    reply["version"])
            acked.add(k)

        for k, vlist in zip(keys, vals):
            merged = _merge_devices(vlist).asnumpy()
            shards = self._shards(k, merged.size)
            if shards is None:
                _push_one(k, merged, self._socks[self._server_of(k)])
                continue
            flat = merged.reshape(-1)
            for sk, sid, sl in shards:
                _push_one(sk, flat[sl], self._socks[sid])

    def pull(self, key, out=None, priority=0):
        import numpy as _np

        from .ndarray import array

        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            size = int(_np.prod(olist[0].shape)) if olist else 0
            tele = _telemetry.enabled()
            t0 = _time.perf_counter() if tele else 0.0
            shards = self._shards(k, size)
            if shards is None:
                reply = self._rpc(self._with_trace(self._with_epoch(
                    {"cmd": "pull", "key": k,
                     "version": self._versions.get(k, 0)})),
                    sock=self._socks[self._server_of(k)])
                val = array(reply["value"])
            else:
                flat = None
                for sk, sid, sl in shards:
                    reply = self._rpc(self._with_trace(self._with_epoch(
                        {"cmd": "pull", "key": sk,
                         "version": self._versions.get(sk, 0)})),
                        sock=self._socks[sid])
                    part = _np.asarray(reply["value"])
                    if flat is None:
                        # dtype follows the stored shards — a hardcoded
                        # f32 buffer would silently cast f64/int/bf16 keys
                        flat = _np.empty((size,), part.dtype)
                    flat[sl] = part
                val = array(flat.reshape(olist[0].shape))
            if tele:
                _telemetry.observe("kvstore.pull.seconds",
                                   _time.perf_counter() - t0,
                                   store=self._type)
                _telemetry.inc("kvstore.pull.count", store=self._type)
                _telemetry.inc("kvstore.pull.bytes", _nd_nbytes(val),
                               store=self._type)
            for o in olist:
                val.copyto(o)

    def set_optimizer(self, optimizer):
        """Serialize the optimizer to every server (reference
        ``python/mxnet/kvstore.py:232`` pickles it to all servers)."""
        self._optimizer = optimizer
        blob = pickle.dumps(optimizer)
        for s in self._socks:
            self._rpc({"cmd": "set_optimizer", "bytes": blob}, sock=s)

    def set_updater(self, updater):
        # dist mode: updates happen on the server; a locally-set updater
        # is ignored (update_on_kvstore semantics)
        self._updater = None

    _set_updater = set_updater

    def barrier(self):
        with _telemetry.phase("barrier", family="kvstore"):
            self._rpc(self._with_trace(self._with_epoch(
                {"cmd": "barrier", "rank": self._rank})))

    def heartbeat(self):
        """Liveness ping to the scheduler; returns its cluster view
        (``{"live": [ranks...], "num_workers": n}``) and refreshes this
        rank's last-seen time for dead-peer diagnosis."""
        _telemetry.inc("kvstore.heartbeats")
        return self._rpc({"cmd": "heartbeat", "rank": self._rank})

    # -- elastic membership (docs/resilience.md) --------------------------
    @property
    def epoch(self):
        """The membership epoch this worker adopted at its last
        ``reshard_sync`` (None before the first adoption)."""
        return self._epoch

    @property
    def observed_epoch(self):
        """The most recent membership epoch observed on any server reply
        (elastic servers stamp push/pull success replies with theirs):
        the batch-boundary poll compares it against the adopted epoch
        without spending an RPC round-trip per batch.  None before any
        epoch-carrying reply arrives."""
        return self._observed_epoch

    def membership(self):
        """The coordinator's membership view: ``{"epoch": E, "ranks":
        [...], "num_workers": W}``.  The poll's fallback when no reply
        has carried an epoch yet."""
        return self._rpc({"cmd": "membership"})

    def deregister(self):
        """Graceful leave: announce this worker is going away so the
        membership shrinks NOW (one epoch bump) instead of after a
        heartbeat deadline of blocked sync rounds."""
        rep = self._rpc({"cmd": "deregister", "rank": self._rank})
        _telemetry.event("elastic.deregister", rank=self._rank,
                         epoch=rep.get("epoch"))
        return rep

    def reshard_sync(self):
        """Quiesce rendezvous: block until every member of the current
        membership epoch arrives, then ADOPT the released view — the
        epoch, the rank set, the new world size — and reset the per-key
        push/pull bookkeeping, which the coordinator restarted at zero
        when the epoch bumped."""
        rep = self._rpc(self._with_trace(
            {"cmd": "reshard_sync", "rank": self._rank}))
        self._epoch = rep["epoch"]
        self._num_workers = rep["num_workers"]
        self._versions = {}
        self._push_seq = {}
        self._acked_in_failed_push = set()
        self._repush_window = False
        return rep

    def set_reshard_choice(self, choice):
        """Leader half of the adopted-generation rendezvous: announce
        the snapshot generation (``{"epoch": e, "nbatch": k}``, or None
        for no-generation) the whole membership rolls back to, so
        followers load exactly that generation instead of each trusting
        its own possibly-lagging manifest read."""
        return self._rpc(self._with_trace(self._with_epoch(
            {"cmd": "reshard_choice", "rank": self._rank,
             "set": choice})))

    def get_reshard_choice(self):
        """Follower half: block until the leader's announcement lands
        (typed :class:`StaleEpoch` when membership moves mid-wait — the
        reshard cycle restarts)."""
        return self._rpc(self._with_trace(self._with_epoch(
            {"cmd": "reshard_choice", "rank": self._rank})))

    def reshard_commit(self):
        """Post-rehydration barrier (epoch-checked): every member's
        snapshot reloads are visible before any member trains."""
        return self._rpc(self._with_trace(self._with_epoch(
            {"cmd": "reshard_commit", "rank": self._rank})))

    def reload(self, key, value):
        """Rehydration push: set ``key``'s coordinator value from the
        adopted snapshot and reset its version/round bookkeeping — on
        the server AND in this client's counters (other members reset
        theirs when they adopt the epoch at ``reshard_sync``)."""
        import numpy as _np

        rep = self._rpc(self._with_trace(self._with_epoch(
            {"cmd": "reload", "key": key, "value": _np.asarray(value)})),
            sock=self._socks[self._server_of(key)])
        self._versions.pop(key, None)
        self._push_seq.pop(key, None)
        return rep

    def get_updater_states(self):
        """Pickled coordinator-side optimizer updater states, one blob
        per shard server (the elastic snapshot's server-optimizer
        capture)."""
        return [self._rpc({"cmd": "get_updater_states"}, sock=s)["states"]
                for s in self._socks]

    def set_updater_states(self, blobs):
        """Re-install coordinator-side optimizer updater states captured
        by :meth:`get_updater_states` (rehydration half)."""
        if isinstance(blobs, (bytes, bytearray)):
            blobs = [blobs]
        for s, blob in zip(self._socks, blobs):
            self._rpc({"cmd": "set_updater_states", "states": blob},
                      sock=s)

    def send_command_to_servers(self, head, body):
        self._rpc({"cmd": "user_command", "head": head, "body": body})

    def save_optimizer_states(self, fname):
        blobs = self.get_updater_states()
        payload = blobs[0] if len(blobs) == 1 else \
            MULTI_STATES_MAGIC + pickle.dumps(blobs)
        _atomic_write_bytes(fname, payload)

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            data = f.read()
        self.set_updater_states(states_file_blobs(data))

    def close(self):
        """Rank 0 stops the server after a final barrier (the reference's
        kStopServer + barrier_before_exit, ``kvstore_dist.h:44-55``)."""
        if self._sock is None:
            return
        try:
            if not self._elastic:
                # elastic worker lifetimes are decoupled from the
                # server's (workers come and go mid-job): leaving just
                # closes the transport; the operator owns server shutdown
                self.barrier()
                if self._rank == 0:
                    for s in self._socks:
                        self._rpc({"cmd": "stop"}, sock=s)
            else:
                # a deliberately-departing elastic worker announces the
                # leave so the membership shrinks NOW; best-effort — an
                # already-severed transport (or an already-deregistered
                # rank: fit's exception path calls leave() first) falls
                # back to heartbeat-death eviction
                try:
                    self.deregister()
                except Exception:  # noqa: broad-except — closing anyway
                    pass
        finally:
            for s in self._socks:
                s.close()
            self._sock = None
            self._socks = []

    def __del__(self):
        try:
            for s in getattr(self, "_socks", []):
                s.close()
        except (OSError, AttributeError, TypeError):
            pass  # interpreter-shutdown cleanup: sockets may be half-gone


def create(name="local"):
    """reference ``kvstore.cc:17-45`` type dispatch, plus the TPU-native
    ``'mesh'`` device plane (``kvstore_mesh.KVStoreMesh``: the gradient
    exchange dissolves into the jitted step as in-graph GSPMD
    collectives over a device mesh — no server, no transport)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "local_allreduce_device", "device",
             "local_update_cpu", "local_allreduce_cpu",
             "dist_sync", "dist_async", "dist_sync_device",
             "dist_async_device", "dist", "mesh")
    if name not in valid:
        raise MXNetError("unknown kvstore type %r" % name)
    if name == "mesh":
        from .kvstore_mesh import KVStoreMesh

        return KVStoreMesh()
    if name.startswith("dist"):
        return KVStoreDist(name)
    return KVStore(name)
