"""KVStore — the gradient-exchange API (``mx.kv``).

Reference: ``include/mxnet/kvstore.h`` + ``src/kvstore/`` (SURVEY §2.4):
``create(type)``, int/str keys, ``init/push/pull`` with per-key aggregation,
``set_optimizer`` (updater applied where the weights live), rank/num_workers,
barrier, server command protocol.

TPU-native mapping (SURVEY §5.8): there is no parameter server —

* ``local`` / ``device``: single-process aggregation.  Pushed gradient lists
  are summed on device (the ``CommDevice`` analog; on a TPU mesh the sum is
  an XLA ``psum`` compiled into the step — see ``parallel/``), and the
  updater runs on the stored copy.
* ``dist_sync`` / ``dist_async``: multi-process parameter server
  (``kvstore_server.py`` — the ``KVStoreDist``/``KVStoreDistServer`` pair,
  ``src/kvstore/kvstore_dist.h``), wired by the same ``DMLC_*`` env
  protocol and ``tools/launch.py``.  Sync mode gives the reference's
  per-key merge-round barrier + server-side optimizer; on TPU pods the
  gradient plane should instead be in-graph DCN collectives (``parallel/``)
  — the PS covers the update-on-server semantics collectives can't express.

The API surface (push/pull ordering per key, update-on-kvstore semantics) is
preserved so ``Module``/``model.py`` code from the reference runs unchanged.
"""

from __future__ import annotations

import os
import pickle
import time as _time

from .base import MXNetError
from .ndarray import NDArray, zeros

__all__ = ["KVStore", "KVStoreDist", "create"]


def _ctype_key_value(keys, vals):
    """Normalize to (list[key], list[list[NDArray]]) — reference kvstore.py."""
    if isinstance(keys, (int, str)):
        keys = [keys]
        vals = [vals]
    out_vals = []
    for v in vals:
        if isinstance(v, NDArray):
            out_vals.append([v])
        else:
            out_vals.append(list(v))
    return list(keys), out_vals


def _merge_devices(vlist):
    """Sum a pushed per-device NDArray list onto the first device (the
    CommDevice reduce, ``src/kvstore/comm.h:200``)."""
    merged = vlist[0]
    for v in vlist[1:]:
        merged = merged + v.as_in_context(merged.context)
    return merged


class KVStore:
    """reference ``python/mxnet/kvstore.py:35``"""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None

    # -- properties -------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """reference kvstore.py rank — process index."""
        return 0

    @property
    def num_workers(self):
        return 1

    # -- data plane -------------------------------------------------------
    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            self._store[k] = vlist[0].copy()

    def push(self, key, value, priority=0):
        """Aggregate pushed values per key; apply updater if set (the
        reference's server-side/updater-side optimizer application,
        ``kvstore_local.h:49-60``)."""
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            merged = _merge_devices(vlist)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                # default updater is ASSIGN (reference kvstore_local.h: the
                # merged reduce replaces the stored value; aggregation is
                # across the pushed device list, not across pushes)
                merged.copyto(self._store[k])

    def pull(self, key, out=None, priority=0):
        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            for o in olist:
                self._store[k].copyto(o)

    # -- updater / optimizer ----------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """reference kvstore.py:232 — on dist the optimizer is serialized to
        servers; here the updater always runs where the weights live."""
        from .optimizer import get_updater

        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    # -- control plane ----------------------------------------------------
    def barrier(self):
        pass

    def send_command_to_servers(self, head, body):
        """No servers exist; kept for API parity (logged no-op)."""

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not initialized on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not initialized on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


class KVStoreDist(KVStore):
    """Parameter-server worker (reference ``src/kvstore/kvstore_dist.h``).

    Connects to the ``kvstore_server`` over TCP using the reference's env
    wire protocol (``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``).  Per-key
    push/pull ordering is version-gated: each sync push returns the round
    it lands in and subsequent pulls block server-side until that round is
    applied — the recv-buffer var-dependency of ``kvstore_dist.h:93-121``
    expressed as versions instead of engine vars.
    """

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        import socket as _socket

        from . import kvstore_server as ps

        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9090"))
        self._ps = ps
        # multi-server sharding (reference ps-lite: N servers, big arrays
        # split by EncodeKey, kvstore_dist.h:40): server i at port+i;
        # server 0 doubles as the scheduler (ranks, barrier)
        self._num_servers = max(1, int(os.environ.get("DMLC_NUM_SERVER",
                                                      "1")))
        self._bigarray_bound = int(os.environ.get(
            "MXNET_KVSTORE_BIGARRAY_BOUND", "1000000"))
        self._socks = []
        deadline = _time.time() + float(
            os.environ.get("MXNET_KVSTORE_CONNECT_TIMEOUT", "120"))
        def connect_all():
            self._socks = []
            for sid in range(self._num_servers):
                # servers import jax before binding; retry with backoff
                while True:
                    try:
                        self._socks.append(_socket.create_connection(
                            (host, port + sid), timeout=300))
                        break
                    except OSError:
                        if _time.time() > deadline:
                            raise
                        _time.sleep(0.2)
            self._sock = self._socks[0]  # scheduler

        connect_all()
        self._versions = {}
        reg = {"cmd": "register", "role": "worker"}
        worker_id = os.environ.get("DMLC_WORKER_ID")
        if worker_id is None and os.environ.get("DMLC_ROLE") == "worker":
            # under an MPI/slurm *launcher* every rank shares one env; the
            # process-manager rank is the worker identity (dmlc-tracker's
            # mpi backend relies on the same variables).  Gated on DMLC_ROLE
            # so a process merely running inside a slurm/MPI allocation does
            # not silently adopt that rank and collide on rejoin.
            for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_PROCID"):
                if var in os.environ:
                    worker_id = os.environ[var]
                    break
        if worker_id is not None:
            # announce identity so a restarted worker rejoins with its old
            # rank (the reference's ps-lite is_recovery path)
            reg["preferred_rank"] = int(worker_id)
        # a loaded host can drop the just-accepted connection before the
        # register reply (seen as a suite-level flake) — as a clean FIN
        # (recv returns b'' -> MXNetError 'connection lost') or as an
        # RST (ConnectionResetError/BrokenPipeError).  Retrying is only
        # safe when the registration is idempotent server-side, i.e.
        # when preferred_rank identifies this worker (the rejoin path);
        # without an identity a processed-but-unacknowledged register
        # would leak a ghost rank on retry, so that case still raises.
        while True:
            try:
                reply = self._rpc(reg)
                break
            except (MXNetError, OSError) as e:
                dropped = isinstance(e, OSError) \
                    or "connection lost" in str(e)
                if not dropped or "preferred_rank" not in reg \
                        or _time.time() > deadline:
                    raise
                for s in self._socks:
                    try:
                        s.close()
                    except OSError:
                        pass
                _time.sleep(0.3)
                connect_all()
        self._rank = reply["rank"]
        self._num_workers = reply["num_workers"]
        self.is_recovery = bool(reply.get("is_recovery", False))
        self._update_on_kvstore = True
        # command every server into the mode this type implies (reference
        # kvstore.cc:32-35: sync unless the type carries _async)
        for s in self._socks:
            self._rpc({"cmd": "sync_mode", "value": "_async" not in kv_type},
                      sock=s)
        # TPU-native gradient plane: join the jax.distributed process
        # group so training steps run in-graph collectives across
        # processes (psum over the global mesh) instead of per-step PS
        # push/pull.  dist_async keeps the PS plane — asynchronous
        # updates have no collective analog (SURVEY §5.8).
        self.in_graph_sync = False
        if "_async" not in kv_type:
            from . import dist as _dist

            self.in_graph_sync = _dist.init_from_env(rank_hint=self._rank)

    def _rpc(self, msg, sock=None):
        sock = self._sock if sock is None else sock
        self._ps.send_msg(sock, msg)
        reply = self._ps.recv_msg(sock)
        if reply is None:
            raise MXNetError("kvstore server connection lost")
        if "error" in reply:
            raise MXNetError(reply["error"])
        return reply

    def _server_of(self, key):
        """Small keys live whole on one server (round-robin by key)."""
        try:
            return int(key) % self._num_servers
        except (TypeError, ValueError):
            return hash(str(key)) % self._num_servers

    def _shards(self, key, size):
        """[(subkey, server, slice)] — arrays over the bigarray bound
        split into one contiguous chunk per server (EncodeKey analog)."""
        n = self._num_servers
        if n == 1 or size < self._bigarray_bound:
            return None
        bounds = [size * i // n for i in range(n + 1)]
        return [("%s#%d" % (key, i), i, slice(bounds[i], bounds[i + 1]))
                for i in range(n) if bounds[i + 1] > bounds[i]]

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            # first init wins on the server (rank-0 broadcast semantics,
            # kvstore_dist.h:58-76)
            v = vlist[0].asnumpy()
            shards = self._shards(k, v.size)
            if shards is None:
                self._rpc({"cmd": "init", "key": k, "value": v},
                          sock=self._socks[self._server_of(k)])
            else:
                flat = v.reshape(-1)
                for sk, sid, sl in shards:
                    self._rpc({"cmd": "init", "key": sk,
                               "value": flat[sl]}, sock=self._socks[sid])
        self.barrier()

    def push(self, key, value, priority=0):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            merged = _merge_devices(vlist).asnumpy()
            shards = self._shards(k, merged.size)
            if shards is None:
                reply = self._rpc({"cmd": "push", "key": k,
                                   "value": merged, "rank": self._rank},
                                  sock=self._socks[self._server_of(k)])
                self._versions[k] = max(self._versions.get(k, 0),
                                        reply["version"])
                continue
            flat = merged.reshape(-1)
            for sk, sid, sl in shards:
                reply = self._rpc({"cmd": "push", "key": sk,
                                   "value": flat[sl], "rank": self._rank},
                                  sock=self._socks[sid])
                self._versions[sk] = max(self._versions.get(sk, 0),
                                         reply["version"])

    def pull(self, key, out=None, priority=0):
        import numpy as _np

        from .ndarray import array

        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            size = int(_np.prod(olist[0].shape)) if olist else 0
            shards = self._shards(k, size)
            if shards is None:
                reply = self._rpc({"cmd": "pull", "key": k,
                                   "version": self._versions.get(k, 0)},
                                  sock=self._socks[self._server_of(k)])
                val = array(reply["value"])
            else:
                flat = None
                for sk, sid, sl in shards:
                    reply = self._rpc(
                        {"cmd": "pull", "key": sk,
                         "version": self._versions.get(sk, 0)},
                        sock=self._socks[sid])
                    part = _np.asarray(reply["value"])
                    if flat is None:
                        # dtype follows the stored shards — a hardcoded
                        # f32 buffer would silently cast f64/int/bf16 keys
                        flat = _np.empty((size,), part.dtype)
                    flat[sl] = part
                val = array(flat.reshape(olist[0].shape))
            for o in olist:
                val.copyto(o)

    def set_optimizer(self, optimizer):
        """Serialize the optimizer to every server (reference
        ``python/mxnet/kvstore.py:232`` pickles it to all servers)."""
        self._optimizer = optimizer
        blob = pickle.dumps(optimizer)
        for s in self._socks:
            self._rpc({"cmd": "set_optimizer", "bytes": blob}, sock=s)

    def set_updater(self, updater):
        # dist mode: updates happen on the server; a locally-set updater
        # is ignored (update_on_kvstore semantics)
        self._updater = None

    _set_updater = set_updater

    def barrier(self):
        self._rpc({"cmd": "barrier", "rank": self._rank})

    def send_command_to_servers(self, head, body):
        self._rpc({"cmd": "user_command", "head": head, "body": body})

    def save_optimizer_states(self, fname):
        blobs = [self._rpc({"cmd": "get_updater_states"},
                           sock=s)["states"] for s in self._socks]
        with open(fname, "wb") as f:
            f.write(blobs[0] if len(blobs) == 1 else
                    b"MXPSMULTI" + pickle.dumps(blobs))

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            data = f.read()
        if data.startswith(b"MXPSMULTI"):
            blobs = pickle.loads(data[len(b"MXPSMULTI"):])
            for s, blob in zip(self._socks, blobs):
                self._rpc({"cmd": "set_updater_states", "states": blob},
                          sock=s)
        else:
            self._rpc({"cmd": "set_updater_states", "states": data})

    def close(self):
        """Rank 0 stops the server after a final barrier (the reference's
        kStopServer + barrier_before_exit, ``kvstore_dist.h:44-55``)."""
        if self._sock is None:
            return
        try:
            self.barrier()
            if self._rank == 0:
                for s in self._socks:
                    self._rpc({"cmd": "stop"}, sock=s)
        finally:
            for s in self._socks:
                s.close()
            self._sock = None
            self._socks = []

    def __del__(self):
        try:
            for s in getattr(self, "_socks", []):
                s.close()
        except Exception:
            pass


def create(name="local"):
    """reference ``kvstore.cc:17-45`` type dispatch."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "local_allreduce_device", "device",
             "local_update_cpu", "local_allreduce_cpu",
             "dist_sync", "dist_async", "dist_sync_device",
             "dist_async_device", "dist")
    if name not in valid:
        raise MXNetError("unknown kvstore type %r" % name)
    if name.startswith("dist"):
        return KVStoreDist(name)
    return KVStore(name)
