"""KVStore — the gradient-exchange API (``mx.kv``).

Reference: ``include/mxnet/kvstore.h`` + ``src/kvstore/`` (SURVEY §2.4):
``create(type)``, int/str keys, ``init/push/pull`` with per-key aggregation,
``set_optimizer`` (updater applied where the weights live), rank/num_workers,
barrier, server command protocol.

TPU-native mapping (SURVEY §5.8): there is no parameter server —

* ``local`` / ``device``: single-process aggregation.  Pushed gradient lists
  are summed on device (the ``CommDevice`` analog; on a TPU mesh the sum is
  an XLA ``psum`` compiled into the step — see ``parallel/``), and the
  updater runs on the stored copy.
* ``dist_sync`` / ``dist_async``: multi-process over DCN via
  ``jax.distributed`` + host collectives.  ``dist_async`` has no collective
  analog (SURVEY §5.8) — it is accepted and behaves bulk-synchronously; the
  semantic difference is documented, not emulated.

The API surface (push/pull ordering per key, update-on-kvstore semantics) is
preserved so ``Module``/``model.py`` code from the reference runs unchanged.
"""

from __future__ import annotations

import pickle

from .base import MXNetError
from .ndarray import NDArray, zeros

__all__ = ["KVStore", "create"]


def _ctype_key_value(keys, vals):
    """Normalize to (list[key], list[list[NDArray]]) — reference kvstore.py."""
    if isinstance(keys, (int, str)):
        keys = [keys]
        vals = [vals]
    out_vals = []
    for v in vals:
        if isinstance(v, NDArray):
            out_vals.append([v])
        else:
            out_vals.append(list(v))
    return list(keys), out_vals


class KVStore:
    """reference ``python/mxnet/kvstore.py:35``"""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None

    # -- properties -------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """reference kvstore.py rank — process index."""
        import jax

        return jax.process_index() if "dist" in self._type else 0

    @property
    def num_workers(self):
        import jax

        return jax.process_count() if "dist" in self._type else 1

    # -- data plane -------------------------------------------------------
    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            self._store[k] = vlist[0].copy()

    def push(self, key, value, priority=0):
        """Aggregate pushed values per key; apply updater if set (the
        reference's server-side/updater-side optimizer application,
        ``kvstore_local.h:49-60``)."""
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            merged = vlist[0]
            for v in vlist[1:]:
                merged = merged + v.as_in_context(merged.context)
            if self.num_workers > 1:
                merged = self._allreduce(merged)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                # default updater is ASSIGN (reference kvstore_local.h: the
                # merged reduce replaces the stored value; aggregation is
                # across the pushed device list, not across pushes)
                merged.copyto(self._store[k])

    def pull(self, key, out=None, priority=0):
        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            for o in olist:
                self._store[k].copyto(o)

    def _allreduce(self, arr):
        """DCN all-reduce across processes (dist types)."""
        import jax
        import jax.numpy as jnp
        from jax.experimental import multihost_utils

        summed = multihost_utils.process_allgather(arr._jx)
        return NDArray._from_jax(jnp.sum(summed, axis=0), arr.context)

    # -- updater / optimizer ----------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """reference kvstore.py:232 — on dist the optimizer is serialized to
        servers; here the updater always runs where the weights live."""
        from .optimizer import get_updater

        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    # -- control plane ----------------------------------------------------
    def barrier(self):
        if self.num_workers > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")

    def send_command_to_servers(self, head, body):
        """No servers exist; kept for API parity (logged no-op)."""

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not initialized on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not initialized on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def create(name="local"):
    """reference ``kvstore.cc:17-45`` type dispatch."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "local_allreduce_device", "device",
             "local_update_cpu", "local_allreduce_cpu",
             "dist_sync", "dist_async", "dist_sync_device",
             "dist_async_device", "dist")
    if name not in valid:
        raise MXNetError("unknown kvstore type %r" % name)
    return KVStore(name)
