"""Checkpointing + kvstore training helpers + legacy FeedForward.

Reference: ``python/mxnet/model.py`` (SURVEY §2.6): ``save_checkpoint/
load_checkpoint`` (prefix-symbol.json + prefix-%04d.params), the kvstore
helper trio used by Module (``_create_kvstore`` :40,
``_update_params_on_kvstore`` :88, ``_update_params`` :99), and the old
``FeedForward`` estimator API.
"""

from __future__ import annotations

import glob as _glob
import hashlib as _hashlib
import json
import logging
import os
import re as _re
import threading as _threading

import numpy as np

from . import io as mxio
from . import ndarray as nd
from . import symbol as sym
from . import telemetry as _telemetry
from .base import (MXNetError, atomic_write as _atomic_write,
                   atomic_write_bytes as _atomic_write_bytes)
from .context import cpu
from .initializer import Uniform
from .kvstore import KVStore, create as _create_kv
from .ndarray import NDArray

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "checkpoint_manifest", "list_checkpoints",
           "load_latest_checkpoint", "FeedForward"]


class BatchEndParam:
    """reference model.py BatchEndParams namedtuple, extended with the
    NaN-guard observation fields (``nan_detected``/``nan_action``) so
    callbacks and metrics can see when a batch tripped the policy."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None,
                 nan_detected=False, nan_action=None,
                 anomaly_detected=False, anomaly_action=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals
        self.nan_detected = nan_detected
        self.nan_action = nan_action
        # statistical-anomaly observation fields (sentinel
        # ``anomaly_policy``), mirroring the NaN pair
        self.anomaly_detected = anomaly_detected
        self.anomaly_action = anomaly_action


def _create_kvstore(kvstore, num_device, arg_params):
    """reference ``model.py:40``"""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if kvstore == "mesh":
            # the mesh device plane spans ALL jax devices regardless of
            # the module's declared context count — never shortcut to None
            kv = _create_kv("mesh")
        elif num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = _create_kv(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(p.shape) for p in arg_params.values())
                if max_size < 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    elif getattr(kv, "in_graph_sync", False):
        # TPU-native dist_sync: gradients reduce in-graph (psum over the
        # global mesh); every worker applies the identical update locally,
        # so the server-side optimizer plane is bypassed
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """reference ``model.py:79``"""
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore):
    """reference ``model.py:88``"""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        kvstore.push(index, grad_list, priority=-index)
        kvstore.pull(index, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None):
    """reference ``model.py:99``"""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        if kvstore:
            kvstore.push(index, grad_list, priority=-index)
            kvstore.pull(index, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


def _manifest_path(prefix):
    return "%s-manifest.json" % prefix


def _sha256_file(path):
    """Hex sha256 of a file, streamed (checkpoint payloads can be GBs)."""
    h = _hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


#: one writer at a time for a manifest read-modify-write: the async
#: snapshot writer thread and fit's epoch-boundary save share a prefix
_MANIFEST_LOCK = _threading.Lock()


def checkpoint_manifest(prefix):
    """Read ``prefix-manifest.json`` -> dict, or None when absent/corrupt.

    Format (version 2; version-1 files load unchanged)::

        {"format": 2, "prefix": "<basename>", "epochs": [1, 2, 3],
         "latest": 3,
         "payload_sha256": {"3": "<hex>"},
         "snapshots": [{"epoch": 2, "nbatch": 17,
                        "params": "<basename>-snap-0002-000017.params",
                        "sha256": "<hex>", "states": ..., "rng_state": ...,
                        "metric_state": ..., "iter_state": ...}]}

    ``epochs`` lists every epoch whose params file completed its atomic
    rename; ``latest`` is ``max(epochs)``.  ``snapshots`` lists the
    retained mid-epoch generations (``mxnet_tpu.checkpoint``), each with
    the sha256 of its payload files and the host-side state (RNG /
    metric / iterator) an exact resume needs.  The manifest itself is
    written atomically, so it never names a file still in flight."""
    try:
        with open(_manifest_path(prefix)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(m, dict) or not isinstance(m.get("epochs"), list) \
            or not all(isinstance(e, int) for e in m["epochs"]):
        return None
    if not isinstance(m.get("snapshots", []), list):
        return None
    return m


def _manifest_mutate(prefix, fn, durable=True):
    """Atomic read-modify-write of the manifest under the process lock.
    ``fn(m)`` edits the dict in place; the result is committed via
    ``atomic_write`` so readers see old-or-new, never a torn file.
    ``durable=False`` (the snapshot hot path) skips the fsyncs — see
    ``base.atomic_write``."""
    with _MANIFEST_LOCK:
        m = checkpoint_manifest(prefix) or {
            "format": 2, "prefix": os.path.basename(prefix), "epochs": []}
        m["format"] = 2
        fn(m)
        blob = json.dumps(m, indent=2, sort_keys=True)
        _atomic_write_bytes(_manifest_path(prefix), blob, mode="w",
                            durable=durable)
        return m


def _manifest_add_epoch(prefix, epoch, sha256=None):
    def _add(m):
        epochs = sorted(set(int(e) for e in m["epochs"]) | {int(epoch)})
        m["epochs"] = epochs
        m["latest"] = epochs[-1]
        if sha256 is not None:
            m.setdefault("payload_sha256", {})[str(int(epoch))] = sha256

    _manifest_mutate(prefix, _add)


def _snap_key(entry):
    return (int(entry.get("epoch", -1)), int(entry.get("nbatch", -1)))


def _manifest_add_snapshot(prefix, entry):
    def _add(m):
        snaps = [s for s in m.get("snapshots", [])
                 if _snap_key(s) != _snap_key(entry)]
        snaps.append(entry)
        m["snapshots"] = sorted(snaps, key=_snap_key)

    _manifest_mutate(prefix, _add, durable=False)


def _manifest_prune_snapshots(prefix, keep_last):
    """Drop all but the newest ``keep_last`` snapshot entries from the
    manifest; returns the PRUNED entries (payload files still on disk —
    the caller unlinks them after this commit, the crash-safe order).
    Skips the manifest rewrite entirely when nothing needs pruning."""
    with _MANIFEST_LOCK:
        m = checkpoint_manifest(prefix)
    if m is None or len(m.get("snapshots", [])) <= keep_last:
        return []
    pruned = []

    def _prune(m):
        snaps = sorted(m.get("snapshots", []), key=_snap_key)
        if len(snaps) > keep_last:
            pruned.extend(snaps[:-keep_last])
            snaps = snaps[-keep_last:]
        m["snapshots"] = snaps

    _manifest_mutate(prefix, _prune, durable=False)
    return pruned


def list_checkpoints(prefix):
    """Epochs with an on-disk params file, newest first.

    The manifest is the primary source; files present on disk but missing
    from it (older framework versions, hand-copied checkpoints) are merged
    in, so resume never ignores a checkpoint that actually exists."""
    found = set()
    m = checkpoint_manifest(prefix)
    if m is not None:
        found.update(int(e) for e in m["epochs"])
    # epochs >= 10000 render as 5+ digits under %04d, and the prefix may
    # contain glob metacharacters — escape it and let the regex decide
    pat = _re.compile(_re.escape(os.path.basename(prefix)) +
                      r"-(\d{4,})\.params$")
    for path in _glob.glob("%s-*.params" % _glob.escape(prefix)):
        mt = pat.search(os.path.basename(path))
        if mt:
            found.add(int(mt.group(1)))
    return sorted((e for e in found
                   if os.path.exists("%s-%04d.params" % (prefix, e))),
                  reverse=True)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """reference ``model.py:319`` — prefix-symbol.json + prefix-%04d.params.

    Both files are written crash-safely (temp file + fsync + atomic
    rename), and ``prefix-manifest.json`` records the epoch only after the
    params rename completed — a host dying mid-save leaves the previous
    checkpoint fully intact and the manifest pointing at it."""
    if symbol is not None:
        _atomic_write("%s-symbol.json" % prefix, symbol.save)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    _atomic_write(param_name, lambda tmp: nd.save(tmp, save_dict),
                  fault_point="checkpoint.write")
    # digest of the renamed payload, recorded in the manifest so resume
    # can verify the bytes before trusting them (a crash between the
    # rename and this manifest write leaves the previous entry intact)
    _manifest_add_epoch(prefix, epoch, sha256=_sha256_file(param_name))
    logging.info("Saved checkpoint to \"%s\"", param_name)


def load_checkpoint(prefix, epoch):
    """reference ``model.py:349``"""
    symbol = sym.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


def load_latest_checkpoint(prefix, logger=logging):
    """Newest checkpoint that passes a full load-verify pass.

    Walks ``list_checkpoints`` newest-first; every candidate whose
    sha256 is recorded in the manifest re-verifies the payload digest
    BEFORE load, and every candidate additionally takes a full
    load-verify pass — a truncated, bit-flipped or otherwise corrupt
    params file is skipped with a warning (never a crash), counted as
    ``resilience.checkpoint.corrupt_skipped``, and the next-older epoch
    is tried.  Returns ``(epoch, symbol, arg_params, aux_params)`` or
    None when no loadable checkpoint exists — the
    ``Module.fit(resume="auto")`` discovery pass."""
    m = checkpoint_manifest(prefix) or {}
    shas = m.get("payload_sha256") or {}
    for epoch in list_checkpoints(prefix):
        params = "%s-%04d.params" % (prefix, epoch)
        want = shas.get(str(epoch))
        if want is not None and _sha256_file(params) != want:
            logger.warning(
                "checkpoint %s failed sha256 verification against the "
                "manifest; falling back to the previous epoch", params)
            _telemetry.inc("resilience.checkpoint.corrupt_skipped")
            continue
        try:
            symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        except (MXNetError, OSError, ValueError) as e:
            logger.warning(
                "checkpoint %s-%04d.params failed verification (%s); "
                "falling back to the previous epoch", prefix, epoch, e)
            _telemetry.inc("resilience.checkpoint.corrupt_skipped")
            continue
        return (epoch, symbol, arg_params, aux_params)
    return None


class FeedForward:
    """Legacy estimator API (reference ``model.py:387``) — a thin veneer
    over Module, kept because the reference examples/tests use it."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=Uniform(0.01), numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        self.symbol = symbol
        self.ctx = ctx if ctx is not None else [cpu()]
        if not isinstance(self.ctx, (list, tuple)):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs.copy()
        self._module = None

    def _get_module(self):
        from .module import Module

        if self._module is None:
            label_names = [n for n in self.symbol.list_arguments()
                           if n.endswith("label")]
            self._module = Module(self.symbol, context=self.ctx,
                                  label_names=label_names or None)
        return self._module

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        data = self._prepare_data(X, y)
        mod = self._get_module()
        force_init = False
        if mod.binded and not mod.for_training:
            # predict() may have bound the shared module for inference
            # (no gradient arrays, grad_req null): training needs a real
            # rebind, not a reshape.  Force re-init so a predict-first
            # module (whose "params" were never initialized) starts from
            # the initializer / self.arg_params, not allocator leftovers.
            mod.bind(data.provide_data, data.provide_label or None,
                     for_training=True, force_rebind=True)
            force_init = True
        elif mod.binded and [tuple(d[1]) for d in mod.data_shapes] != \
                [tuple(d[1]) for d in data.provide_data]:
            # the shared module may have been reshaped by predict();
            # bring it back to the training shapes before fitting
            mod.reshape(data.provide_data, data.provide_label or None)
        mod.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=self.kwargs or {"learning_rate": 0.01},
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch, monitor=monitor,
                force_init=force_init)
        self.arg_params, self.aux_params = mod.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data = self._prepare_data(X)
        mod = self._get_module()
        if not mod.binded:
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label or None,
                     for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=True)
        elif [tuple(d[1]) for d in mod.data_shapes] != \
                [tuple(d[1]) for d in data.provide_data]:
            # a module bound by fit() at the training batch size serves
            # prediction at another batch size via reshape (the reference
            # rebuilds its _pred_exec the same way).  The training label
            # shapes must survive at the new batch size — dropping them
            # would make a later fit() silently train on zero labels.
            new_batch = tuple(data.provide_data[0][1])[0]
            label_shapes = [(d[0], (new_batch,) + tuple(d[1])[1:])
                            for d in (mod.label_shapes or [])] or None
            mod.reshape(data.provide_data, label_shapes)
        if reset:
            data.reset()
        outputs = mod.predict(data, num_batch=num_batch)
        out = outputs[0] if isinstance(outputs, list) and len(outputs) == 1 \
            else outputs
        return out.asnumpy() if isinstance(out, NDArray) else out

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        data = self._prepare_data(X)
        mod = self._get_module()
        if not mod.binded:
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label, for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=True)
        res = mod.score(data, eval_metric, num_batch=num_batch)
        return res[0][1]

    def _prepare_data(self, X, y=None):
        if isinstance(X, mxio.DataIter):
            return X
        # reference model.py clamps on the SAMPLE count: small numpy
        # inputs must not be rejected by a larger default
        # numpy_batch_size (NDArrayIter also accepts list/dict inputs,
        # whose len() is the number of arrays, not samples)
        if isinstance(X, dict):
            first = next(iter(X.values()))
        elif isinstance(X, (list, tuple)):
            first = X[0]
        else:
            first = X
        batch = min(first.shape[0], self.numpy_batch_size)
        return mxio.NDArrayIter(X, y, batch_size=batch, shuffle=False)

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=Uniform(0.01), eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
